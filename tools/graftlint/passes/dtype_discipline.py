"""dtype-discipline: no 64-bit integers in the jnp world.

Invariant: this codebase runs with ``jax_enable_x64`` OFF (the default),
so every ``jnp`` integer array is at most 32 bits.  ``jnp.int64`` /
``jnp.uint64`` silently alias their 32-bit cousins, and an integer
literal wider than 32 bits flowing into a ``jnp`` constructor truncates
without warning — positions are ``row*2^20 + col`` uint64 values on the
host, so one careless hand-off corrupts data instead of erroring.  Wide
integers must stay in host numpy (uint64 end to end) and cross to the
device only after an explicit width reduction.
"""

from __future__ import annotations

import ast

from tools.graftlint._astutil import dotted
from tools.graftlint.engine import Finding

PASS_ID = "dtype-discipline"
DESCRIPTION = "no int64/uint64 dtypes or >32-bit literals in jnp calls"

_BAD_DTYPE_DOTTED = {
    "jnp.int64", "jnp.uint64",
    "jax.numpy.int64", "jax.numpy.uint64",
    "np.int64", "np.uint64", "numpy.int64", "numpy.uint64",
}
_BAD_DTYPE_STRS = {"int64", "uint64"}
_JNP_ROOTS = ("jnp.", "jax.numpy.")

_INT32_MIN = -(2**31)
_UINT32_MAX = 2**32 - 1


def applies(path: str) -> bool:
    return True


def _is_jnp_call(call: ast.Call) -> bool:
    d = dotted(call.func)
    return d is not None and d.startswith(_JNP_ROOTS)


def _bad_dtype_expr(node: ast.AST) -> str | None:
    d = dotted(node)
    if d in _BAD_DTYPE_DOTTED:
        return d
    if isinstance(node, ast.Constant) and node.value in _BAD_DTYPE_STRS:
        return repr(node.value)
    return None


def check(path: str, tree: ast.AST, lines: list[str]) -> list[Finding]:
    findings: list[Finding] = []
    seen: set[tuple[int, int]] = set()

    def flag(node: ast.AST, msg: str) -> None:
        # nested jnp calls re-walk their arguments; report each site once
        if (node.lineno, node.col_offset) in seen:
            return
        seen.add((node.lineno, node.col_offset))
        findings.append(Finding(path, node.lineno, node.col_offset, PASS_ID, msg))

    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute):
            d = dotted(node)
            if d in ("jnp.int64", "jnp.uint64", "jax.numpy.int64", "jax.numpy.uint64"):
                flag(
                    node,
                    f"{d} with x64 disabled silently means the 32-bit dtype",
                )
        if not isinstance(node, ast.Call) or not _is_jnp_call(node):
            continue
        for kw in node.keywords:
            if kw.arg == "dtype":
                bad = _bad_dtype_expr(kw.value)
                # jnp.int64-style dtypes are already caught by the
                # attribute rule above
                if bad is not None and not bad.startswith(("jnp.", "jax.numpy.")):
                    flag(
                        kw.value,
                        f"dtype={bad} passed to {dotted(node.func)}: 64-bit "
                        "ints truncate to 32 with x64 disabled",
                    )
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for sub in ast.walk(arg):
                if (
                    isinstance(sub, ast.Constant)
                    and isinstance(sub.value, int)
                    and not isinstance(sub.value, bool)
                    and (sub.value > _UINT32_MAX or sub.value < _INT32_MIN)
                ):
                    flag(
                        sub,
                        f"integer literal {sub.value} (needs >32 bits) inside "
                        f"{dotted(node.func)}(...): truncates with x64 disabled",
                    )
    return findings
