"""durability: write-then-rename / write-then-close must fsync.

Invariant: storage/ promises the reference's crash durability (snapshot
rewrites are atomic temp-file+rename, the op log survives clean
shutdown).  ``os.replace``/``os.rename`` of freshly written bytes is
only atomic-AND-durable if those bytes were fsync'd first — otherwise a
power cut can leave the renamed file empty or torn.  Likewise a
``close()`` that hands a data-file handle back to the OS without fsync
leaves the tail of the op log in the page cache (the exact bug class of
the round-5 ADVICE medium finding on FragmentFile.close).

Heuristics, per function in storage/:

* calls ``os.replace``/``os.rename`` but never ``os.fsync`` (or a
  ``*sync*``-named helper) -> finding;
* is named ``close`` and closes a file-handle-looking ``self``
  attribute (``_fh``, ``_file``, ``fh``, ``_log`` ...) without an fsync
  on some path through the function -> finding.
"""

from __future__ import annotations

import ast
import re

from tools.graftlint._astutil import dotted
from tools.graftlint.engine import Finding

PASS_ID = "durability"
DESCRIPTION = "storage/: rename or data-file close without an os.fsync"

_RENAMES = {"os.replace", "os.rename"}
_HANDLE_ATTR_RE = re.compile(r"(^|_)(fh|file|log|wal)$")


def applies(path: str) -> bool:
    return "/storage/" in path


def _has_sync(calls: list[str]) -> bool:
    return any(
        d == "os.fsync" or d.split(".")[-1].find("sync") >= 0 for d in calls
    )


def check(path: str, tree: ast.AST, lines: list[str]) -> list[Finding]:
    findings: list[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        calls: list[str] = []
        rename_node: ast.Call | None = None
        close_node: ast.Call | None = None
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d is not None:
                calls.append(d)
                if d in _RENAMES and rename_node is None:
                    rename_node = node
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "close"
                and isinstance(node.func.value, ast.Attribute)
                and _HANDLE_ATTR_RE.search(node.func.value.attr)
                and close_node is None
            ):
                close_node = node
        if _has_sync(calls):
            continue
        if rename_node is not None:
            findings.append(
                Finding(
                    path, rename_node.lineno, rename_node.col_offset, PASS_ID,
                    f"{dotted(rename_node.func)} in {fn.name!r} without an "
                    "os.fsync: the renamed bytes may not survive a power cut",
                )
            )
        if fn.name == "close" and close_node is not None:
            attr = close_node.func.value.attr  # type: ignore[union-attr]
            findings.append(
                Finding(
                    path, close_node.lineno, close_node.col_offset, PASS_ID,
                    f"close() releases self.{attr} without os.fsync: "
                    "page-cache tail of the data file can be lost on crash",
                )
            )
    return findings
