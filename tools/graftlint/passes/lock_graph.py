"""lock-graph: whole-program lock acquisition-order analysis.

The serving plane runs many long-lived threads (batcher dispatcher,
ingest uploader, flight recorder, resize coordinator, membership
monitor, importpool workers, prefetcher) against shared state guarded by
per-class ``threading.Lock``/``RLock`` fields.  Two threads that acquire
the same pair of locks in opposite orders deadlock the first time their
schedules interleave — a bug no single-file lint can see, because the
two halves of the inversion live in different modules (the classic
example this pass exists for: ``core/membudget.py`` evict callbacks vs
``core/fragment.py`` device sync).

Eraser-style lockset analysis, statically:

* **lock identity** — a lock is ``(class, attr)`` for ``self._x =
  threading.Lock()`` fields (every instance of the class maps to one
  node: order must be consistent *per class*, which is also what the
  runtime witness in ``pilosa_tpu/testing/lockwitness.py`` keys on) or
  ``(module, name)`` for module-level locks.  ``threading.Condition(L)``
  aliases to its underlying lock.
* **held sets** — ``with self._lock:`` opens a region; direct nested
  acquisitions and *interprocedural* acquisitions (calls resolved
  through tools/graftlint/callgraph.py, transitively) add edges
  ``held → acquired`` to the global acquisition-order graph.
* **report** — every cycle in the graph is a potential deadlock; the
  finding prints one witness path per edge as ``file:line → file:line``
  (the with-statement that holds, the call chain, the acquisition).

Deliberate under-approximation (documented so suppressions can cite it):
explicit ``.acquire()`` calls are ignored (the tree's only ones are
non-blocking try-acquires, which cannot wait and so cannot deadlock),
self-edges are skipped (RLock re-entrancy and the shared class-level
identity make them overwhelmingly false), and unresolvable dynamic calls
truncate the walk.  The runtime witness covers the remainder: an
inversion the static graph misses shows up as a runtime-only edge.
"""

from __future__ import annotations

import ast
import os

from tools.graftlint.callgraph import CallGraph, FuncInfo, _dotted
from tools.graftlint.engine import Finding

PASS_ID = "lock-graph"
DESCRIPTION = "whole-program lock acquisition-order cycles (potential deadlock)"
PROJECT = True
USES_CALLGRAPH = True

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "Lock", "RLock",
}
_COND_CTORS = {"threading.Condition", "Condition"}


def applies(path: str) -> bool:  # unused for project passes; kept uniform
    return False


def _rel(path: str, root: str) -> str:
    try:
        r = os.path.relpath(path, root)
    except ValueError:  # pragma: no cover - windows drive mismatch
        return path
    return r.replace(os.sep, "/")


class _Analysis:
    def __init__(self, files: dict, graph: CallGraph):
        self.graph = graph
        self.root = graph.root
        # lock id -> human label
        self.locks: dict[str, str] = {}
        # (module, class name or None, attr/name) -> lock id
        self.class_locks: dict[tuple[str, str], str] = {}  # (cls qual, attr)
        self.module_locks: dict[tuple[str, str], str] = {}  # (module, name)
        self._collect_locks(files)
        # per-function facts
        self.direct: dict[str, list] = {}  # qual -> [(lock, site, held)]
        self.calls: dict[str, list] = {}  # qual -> [(callee qual, site, held)]
        for fi in sorted(graph.functions.values(), key=lambda f: f.qualname):
            self._scan_function(fi)
        self.summary = self._summaries()

    # -- lock discovery ------------------------------------------------------

    def _collect_locks(self, files: dict) -> None:
        g = self.graph
        conditions: list[tuple[str, str, ast.Call]] = []
        for ci in sorted(g.classes.values(), key=lambda c: c.qualname):
            for attr, (call, _ln) in sorted(ci.attr_assigns.items()):
                d = _dotted(call.func) or ""
                if d in _LOCK_CTORS:
                    lid = f"{ci.qualname}.{attr}"
                    self.class_locks[(ci.qualname, attr)] = lid
                    self.locks[lid] = f"{ci.name}.{attr}"
                elif d in _COND_CTORS:
                    conditions.append((ci.qualname, attr, call))
        for module in sorted(g.module_tree):
            tree = g.module_tree[module]
            for node in tree.body:
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                ):
                    d = _dotted(node.value.func) or ""
                    name = node.targets[0].id
                    if d in _LOCK_CTORS:
                        lid = f"{module}:{name}"
                        self.module_locks[(module, name)] = lid
                        self.locks[lid] = f"{module}.{name}"
        # Condition(self._x) shares its underlying lock; Condition()
        # owns a fresh one
        for cls_qual, attr, call in conditions:
            lid = None
            if call.args:
                a = call.args[0]
                if (
                    isinstance(a, ast.Attribute)
                    and isinstance(a.value, ast.Name)
                    and a.value.id == "self"
                ):
                    lid = self.class_locks.get((cls_qual, a.attr))
            if lid is None:
                lid = f"{cls_qual}.{attr}"
                self.locks[lid] = f"{cls_qual.split(':')[-1]}.{attr}"
            self.class_locks[(cls_qual, attr)] = lid

    # -- acquisition resolution ----------------------------------------------

    def _lock_of_expr(self, fi: FuncInfo, expr: ast.AST) -> str | None:
        """``with <expr>:`` → lock id, when expr names a known lock."""
        g = self.graph
        if isinstance(expr, ast.Name):
            return self.module_locks.get((fi.module, expr.id))
        if isinstance(expr, ast.Attribute):
            recv = expr.value
            if isinstance(recv, ast.Name):
                if recv.id in ("self", "cls") and fi.cls is not None:
                    for c in g.mro(fi.cls):
                        lid = self.class_locks.get((c.qualname, expr.attr))
                        if lid is not None:
                            return lid
                    return None
                # module-level lock through an import: mod._lock
                imp = g.imports.get(fi.module, {}).get(recv.id)
                if isinstance(imp, str) and imp in g.module_path:
                    return self.module_locks.get((imp, expr.attr))
                # local var of inferred project type: v._lock
                lt = g._local_var_types(fi).get(recv.id)
                if lt is not None:
                    for c in g.mro(lt):
                        lid = self.class_locks.get((c.qualname, expr.attr))
                        if lid is not None:
                            return lid
                return None
            if (
                isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id in ("self", "cls")
                and fi.cls is not None
            ):
                # with self._attr._lock: through the inferred attr type
                at = g.attr_type(fi.cls, recv.attr)
                if at is not None:
                    for c in g.mro(at):
                        lid = self.class_locks.get((c.qualname, expr.attr))
                        if lid is not None:
                            return lid
        return None

    def _scan_function(self, fi: FuncInfo) -> None:
        direct: list = []
        calls: list = []
        root = self.root

        def visit(stmts, held):
            for node in stmts:
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    continue
                if isinstance(node, ast.With):
                    inner = held
                    for item in node.items:
                        expr = item.context_expr
                        lid = self._lock_of_expr(fi, expr)
                        if lid is not None:
                            site = (_rel(fi.path, root), expr.lineno)
                            direct.append((lid, site, inner))
                            inner = inner + ((lid, site),)
                        else:
                            self._scan_expr(fi, expr, inner, calls)
                    visit(node.body, inner)
                    continue
                # non-with statement: scan expressions for calls, then
                # recurse into compound bodies with the same held set
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(node, field, None)
                    if sub:
                        visit(sub, held)
                for h in getattr(node, "handlers", []) or []:
                    visit(h.body, held)
                self._scan_stmt_exprs(fi, node, held, calls)

        visit(fi.node.body, ())
        if direct:
            self.direct[fi.qualname] = direct
        if calls:
            self.calls[fi.qualname] = calls

    def _scan_stmt_exprs(self, fi, node, held, calls) -> None:
        """Record resolvable calls in the *expression* parts of one
        statement (not its nested statement bodies)."""
        for field, value in ast.iter_fields(node):
            if field in ("body", "orelse", "finalbody", "handlers"):
                continue
            vals = value if isinstance(value, list) else [value]
            for v in vals:
                if isinstance(v, ast.AST):
                    self._scan_expr(fi, v, held, calls)

    def _scan_expr(self, fi, expr, held, calls) -> None:
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue  # closures run later, outside this held set
            if isinstance(node, ast.Call):
                target = self.graph.resolve_callable(fi, fi.module, node.func)
                if target is not None:
                    site = (_rel(fi.path, self.root), node.lineno)
                    calls.append((target.qualname, site, held))
            stack.extend(ast.iter_child_nodes(node))

    # -- transitive acquisition summaries ------------------------------------

    def _summaries(self) -> dict:
        """qual -> {lock: chain [(path,line),...]} of every acquisition
        reachable from the function with an EMPTY entry held set."""
        summary: dict[str, dict[str, tuple]] = {}
        for qual in self.graph.functions:
            summary[qual] = {}
        for qual, acqs in self.direct.items():
            for lid, site, _held in acqs:
                cur = summary[qual].get(lid)
                if cur is None or (site,) < cur:
                    summary[qual][lid] = (site,)
        # fixpoint: pull callee summaries through call sites
        changed = True
        iters = 0
        while changed and iters < 50:
            changed = False
            iters += 1
            for qual in sorted(self.calls):
                mine = summary[qual]
                for callee, site, _held in self.calls[qual]:
                    for lid, chain in summary.get(callee, {}).items():
                        cand = (site,) + chain
                        cur = mine.get(lid)
                        if cur is None or len(cand) < len(cur) or (
                            len(cand) == len(cur) and cand < cur
                        ):
                            mine[lid] = cand
                            changed = True
        return summary

    # -- edges + cycles ------------------------------------------------------

    def edges(self) -> dict:
        """{(held, acquired): witness} where witness = (held-site,
        chain-to-acquisition)."""
        out: dict[tuple, tuple] = {}

        def add(a, b, witness):
            if a == b:
                return
            cur = out.get((a, b))
            if cur is None or (len(witness[1]), witness) < (len(cur[1]), cur):
                out[(a, b)] = witness

        for qual in sorted(self.direct):
            for lid, site, held in self.direct[qual]:
                for h, hsite in held:
                    add(h, lid, (hsite, (site,)))
        for qual in sorted(self.calls):
            for callee, site, held in self.calls[qual]:
                if not held:
                    continue
                for lid, chain in self.summary.get(callee, {}).items():
                    for h, hsite in held:
                        add(h, lid, (hsite, (site,) + chain))
        return out


def _fmt_chain(witness) -> str:
    hsite, chain = witness
    steps = [f"{p}:{ln}" for p, ln in (hsite,) + tuple(chain)]
    return " → ".join(steps)


def _cycles(edges: dict) -> list[list[str]]:
    """Deterministic minimal cycles: for every SCC of size >= 2, the
    shortest cycle through its lexicographically-smallest lock."""
    adj: dict[str, list[str]] = {}
    nodes: set[str] = set()
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        nodes.add(a)
        nodes.add(b)
    for a in adj:
        adj[a].sort()

    # iterative Tarjan SCC
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    for start in sorted(nodes):
        if start in index:
            continue
        work = [(start, iter(adj.get(start, [])))]
        index[start] = low[start] = counter[0]
        counter[0] += 1
        stack.append(start)
        on.add(start)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(adj.get(w, []))))
                    advanced = True
                    break
                if w in on:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

    out: list[list[str]] = []
    for comp in sorted(sccs):
        comp_set = set(comp)
        start = comp[0]
        # BFS back to start within the SCC
        prev: dict[str, str] = {}
        frontier = [start]
        found = None
        seen = set()
        while frontier and found is None:
            nxt = []
            for v in frontier:
                for w in adj.get(v, []):
                    if w == start:
                        found = v
                        break
                    if w in comp_set and w not in seen:
                        seen.add(w)
                        prev[w] = v
                        nxt.append(w)
                if found is not None:
                    break
            frontier = nxt
        if found is None:  # pragma: no cover - SCC guarantees a cycle
            continue
        path = [found]
        while path[-1] != start:
            path.append(prev[path[-1]])
        path.reverse()  # start ... found, then found->start closes it
        out.append(path)
    return out


def check_project(files: dict, graph: CallGraph) -> list[Finding]:
    an = _Analysis(files, graph)
    edges = an.edges()
    findings: list[Finding] = []
    for cycle in _cycles(edges):
        hops = []
        for i, a in enumerate(cycle):
            b = cycle[(i + 1) % len(cycle)]
            w = edges[(a, b)]
            hops.append(
                f"{an.locks.get(a, a)} → {an.locks.get(b, b)}"
                f" [{_fmt_chain(w)}]"
            )
        first = edges[(cycle[0], cycle[1 % len(cycle)])]
        anchor_path, anchor_line = first[0]
        names = " → ".join(
            an.locks.get(x, x) for x in cycle + [cycle[0]]
        )
        findings.append(
            Finding(
                _abspath(files, anchor_path), anchor_line, 0, PASS_ID,
                f"lock-order cycle (potential deadlock): {names}; "
                + "; ".join(hops),
            )
        )
    return findings


def _abspath(files: dict, rel: str) -> str:
    """Map a root-relative witness path back to the engine's path key so
    suppression comments in that file apply."""
    for path in files:
        if path.replace(os.sep, "/").endswith(rel):
            return path
    return rel
