"""graftlint core: finding model, suppression parsing, orchestration.

A *pass* is a module under tools/graftlint/passes exposing:

    PASS_ID: str               stable kebab-case id (used in disable=...)
    DESCRIPTION: str           one line for --list-passes
    def applies(path) -> bool  path scope (repo-relative, '/'-separated)
    def check(path, tree, lines) -> list[Finding]

Project-wide passes (cross-file consistency) instead expose:

    PROJECT = True
    def check_project(files: dict[str, tuple[ast.AST, list[str]]]) -> list[Finding]

Whole-program passes that need interprocedural reasoning additionally set
``USES_CALLGRAPH = True`` and receive a shared
:class:`tools.graftlint.callgraph.CallGraph` (built once per run) as a
second argument:

    PROJECT = True
    USES_CALLGRAPH = True
    def check_project(files, graph) -> list[Finding]

Suppression comments (reason MANDATORY after ``--``)::

    # graftlint: disable=<pass>[,<pass>] -- <reason>        (this line only)
    # graftlint: disable-file=<pass>[,<pass>] -- <reason>   (whole file)

A disable without a reason is reported as a ``bad-suppression`` finding
that cannot itself be suppressed.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize


@dataclasses.dataclass
class Finding:
    path: str
    line: int
    col: int
    pass_id: str
    message: str
    suppressed: bool = False
    reason: str | None = None

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        tail = f"  [suppressed: {self.reason}]" if self.suppressed else ""
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"[{self.pass_id}] {self.message}{tail}"
        )


_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(?P<kind>disable(?:-file)?)\s*=\s*"
    r"(?P<passes>[A-Za-z0-9_,-]+)"
    r"(?:\s*--\s*(?P<reason>\S.*?))?\s*$"
)


def _comments(src: str):
    """(line, col, text) of every real COMMENT token — docstrings or
    string literals that merely *mention* the syntax must not count."""
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.start[1], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return


class Suppressions:
    """Parsed disable comments of one file."""

    def __init__(self, path: str, lines: list[str]):
        self.path = path
        # (line, pass_id) -> reason / pass_id -> reason
        self.by_line: dict[tuple[int, str], str] = {}
        self.by_file: dict[str, str] = {}
        self.errors: list[Finding] = []
        for lineno, col, text in _comments("\n".join(lines)):
            m = _SUPPRESS_RE.search(text)
            if not m:
                # catch malformed graftlint comments so a typo'd disable
                # doesn't silently do nothing
                if re.match(r"#\s*graftlint\b", text):
                    self.errors.append(
                        Finding(
                            path, lineno, col, "bad-suppression",
                            "unparseable graftlint comment (expected "
                            "'# graftlint: disable=<pass> -- <reason>')",
                        )
                    )
                continue
            passes = [p for p in m.group("passes").split(",") if p]
            reason = m.group("reason")
            if not reason:
                self.errors.append(
                    Finding(
                        path, lineno, col, "bad-suppression",
                        f"disable={m.group('passes')} has no reason; append "
                        "' -- <why this is safe>'",
                    )
                )
                continue
            for p in passes:
                if m.group("kind") == "disable-file":
                    self.by_file[p] = reason
                else:
                    self.by_line[(lineno, p)] = reason

    def match(self, f: Finding) -> str | None:
        r = self.by_line.get((f.line, f.pass_id))
        if r is not None:
            return r
        return self.by_file.get(f.pass_id)


# Directories never worth descending into.  The bundled corpus is
# deliberately full of violations, so the walker skips it even when the
# caller lints the tools tree itself.
_SKIP_DIRS = {"__pycache__", ".git", ".github", "corpus"}


def walk_files(roots: list[str]) -> list[str]:
    out: list[str] = []
    for root in roots:
        if os.path.isfile(root):
            out.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in _SKIP_DIRS and not d.startswith(".")
            )
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def parse_file(path: str) -> tuple[ast.AST | None, list[str], Finding | None]:
    """(tree, lines, parse_error_finding)."""
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        src = fh.read()
    lines = src.splitlines()
    try:
        return ast.parse(src, filename=path), lines, None
    except SyntaxError as e:
        return None, lines, Finding(
            path, e.lineno or 1, e.offset or 0, "parse",
            f"syntax error: {e.msg}",
        )


def load_passes():
    from tools.graftlint.passes import ALL_PASSES

    return ALL_PASSES


def _check_one_file(path: str, file_passes) -> tuple[list[Finding], dict]:
    """Per-file passes over one file; (findings, {pass_id: seconds}).
    Module-level so ``--jobs`` worker processes can pickle the call."""
    import time as _time

    findings: list[Finding] = []
    timings: dict[str, float] = {}
    tree, lines, err = parse_file(path)
    if err is not None:
        return findings, timings  # the parent reports parse errors
    rel = path.replace(os.sep, "/")
    for p in file_passes:
        if p.applies(rel):
            t0 = _time.perf_counter()
            findings.extend(p.check(path, tree, lines))
            timings[p.PASS_ID] = (
                timings.get(p.PASS_ID, 0.0) + _time.perf_counter() - t0
            )
    return findings, timings


def _worker(path: str) -> tuple[list[Finding], dict]:
    passes = [p for p in load_passes() if not getattr(p, "PROJECT", False)]
    return _check_one_file(path, passes)


def run(
    roots: list[str],
    passes=None,
    jobs: int = 1,
    timings: dict | None = None,
) -> list[Finding]:
    """Lint ``roots``; returns every finding, suppressed ones marked.

    ``jobs > 1`` fans the per-file passes out over a process pool;
    finding order is identical to the serial run (results are folded in
    input-file order, and each file's findings keep pass order).
    Parsing, suppression collection, and the project-wide passes stay in
    the parent: they need every file at once (the call graph is global).
    ``timings``, when a dict, is filled with {pass_id: seconds}.
    """
    import time as _time

    if passes is None:
        passes = load_passes()
    file_passes = [p for p in passes if not getattr(p, "PROJECT", False)]
    project_passes = [p for p in passes if getattr(p, "PROJECT", False)]
    if timings is None:
        timings = {}

    findings: list[Finding] = []
    parsed: dict[str, tuple[ast.AST, list[str]]] = {}
    supp: dict[str, Suppressions] = {}
    paths = walk_files(roots)

    per_file: dict[str, list[Finding]] = {}
    if jobs > 1 and len(paths) > 1:
        import concurrent.futures

        with concurrent.futures.ProcessPoolExecutor(max_workers=jobs) as pool:
            for path, (fs, ts) in zip(paths, pool.map(_worker, paths)):
                per_file[path] = fs
                for pid, sec in ts.items():
                    timings[pid] = timings.get(pid, 0.0) + sec

    for path in paths:
        tree, lines, err = parse_file(path)
        supp[path] = Suppressions(path, lines)
        findings.extend(supp[path].errors)
        if err is not None:
            findings.append(err)
            continue
        parsed[path] = (tree, lines)
        if path in per_file:
            findings.extend(per_file[path])
        else:
            fs, ts = _check_one_file(path, file_passes)
            findings.extend(fs)
            for pid, sec in ts.items():
                timings[pid] = timings.get(pid, 0.0) + sec

    graph = None
    if any(getattr(p, "USES_CALLGRAPH", False) for p in project_passes):
        from tools.graftlint.callgraph import CallGraph

        t0 = _time.perf_counter()
        graph = CallGraph(parsed)
        timings["callgraph-build"] = _time.perf_counter() - t0
    for p in project_passes:
        t0 = _time.perf_counter()
        if getattr(p, "USES_CALLGRAPH", False):
            findings.extend(p.check_project(parsed, graph))
        else:
            findings.extend(p.check_project(parsed))
        timings[p.PASS_ID] = (
            timings.get(p.PASS_ID, 0.0) + _time.perf_counter() - t0
        )

    for f in findings:
        if f.pass_id == "bad-suppression":
            continue  # meta-findings are never suppressable
        s = supp.get(f.path)
        reason = s.match(f) if s is not None else None
        if reason is not None:
            f.suppressed = True
            f.reason = reason
    return findings
