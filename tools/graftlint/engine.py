"""graftlint core: finding model, suppression parsing, orchestration.

A *pass* is a module under tools/graftlint/passes exposing:

    PASS_ID: str               stable kebab-case id (used in disable=...)
    DESCRIPTION: str           one line for --list-passes
    def applies(path) -> bool  path scope (repo-relative, '/'-separated)
    def check(path, tree, lines) -> list[Finding]

Project-wide passes (cross-file consistency) instead expose:

    PROJECT = True
    def check_project(files: dict[str, tuple[ast.AST, list[str]]]) -> list[Finding]

Suppression comments (reason MANDATORY after ``--``)::

    # graftlint: disable=<pass>[,<pass>] -- <reason>        (this line only)
    # graftlint: disable-file=<pass>[,<pass>] -- <reason>   (whole file)

A disable without a reason is reported as a ``bad-suppression`` finding
that cannot itself be suppressed.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize


@dataclasses.dataclass
class Finding:
    path: str
    line: int
    col: int
    pass_id: str
    message: str
    suppressed: bool = False
    reason: str | None = None

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        tail = f"  [suppressed: {self.reason}]" if self.suppressed else ""
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"[{self.pass_id}] {self.message}{tail}"
        )


_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(?P<kind>disable(?:-file)?)\s*=\s*"
    r"(?P<passes>[A-Za-z0-9_,-]+)"
    r"(?:\s*--\s*(?P<reason>\S.*?))?\s*$"
)


def _comments(src: str):
    """(line, col, text) of every real COMMENT token — docstrings or
    string literals that merely *mention* the syntax must not count."""
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.start[1], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return


class Suppressions:
    """Parsed disable comments of one file."""

    def __init__(self, path: str, lines: list[str]):
        self.path = path
        # (line, pass_id) -> reason / pass_id -> reason
        self.by_line: dict[tuple[int, str], str] = {}
        self.by_file: dict[str, str] = {}
        self.errors: list[Finding] = []
        for lineno, col, text in _comments("\n".join(lines)):
            m = _SUPPRESS_RE.search(text)
            if not m:
                # catch malformed graftlint comments so a typo'd disable
                # doesn't silently do nothing
                if re.match(r"#\s*graftlint\b", text):
                    self.errors.append(
                        Finding(
                            path, lineno, col, "bad-suppression",
                            "unparseable graftlint comment (expected "
                            "'# graftlint: disable=<pass> -- <reason>')",
                        )
                    )
                continue
            passes = [p for p in m.group("passes").split(",") if p]
            reason = m.group("reason")
            if not reason:
                self.errors.append(
                    Finding(
                        path, lineno, col, "bad-suppression",
                        f"disable={m.group('passes')} has no reason; append "
                        "' -- <why this is safe>'",
                    )
                )
                continue
            for p in passes:
                if m.group("kind") == "disable-file":
                    self.by_file[p] = reason
                else:
                    self.by_line[(lineno, p)] = reason

    def match(self, f: Finding) -> str | None:
        r = self.by_line.get((f.line, f.pass_id))
        if r is not None:
            return r
        return self.by_file.get(f.pass_id)


# Directories never worth descending into.  The bundled corpus is
# deliberately full of violations, so the walker skips it even when the
# caller lints the tools tree itself.
_SKIP_DIRS = {"__pycache__", ".git", ".github", "corpus"}


def walk_files(roots: list[str]) -> list[str]:
    out: list[str] = []
    for root in roots:
        if os.path.isfile(root):
            out.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in _SKIP_DIRS and not d.startswith(".")
            )
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def parse_file(path: str) -> tuple[ast.AST | None, list[str], Finding | None]:
    """(tree, lines, parse_error_finding)."""
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        src = fh.read()
    lines = src.splitlines()
    try:
        return ast.parse(src, filename=path), lines, None
    except SyntaxError as e:
        return None, lines, Finding(
            path, e.lineno or 1, e.offset or 0, "parse",
            f"syntax error: {e.msg}",
        )


def load_passes():
    from tools.graftlint.passes import ALL_PASSES

    return ALL_PASSES


def run(roots: list[str], passes=None) -> list[Finding]:
    """Lint ``roots``; returns every finding, suppressed ones marked."""
    if passes is None:
        passes = load_passes()
    file_passes = [p for p in passes if not getattr(p, "PROJECT", False)]
    project_passes = [p for p in passes if getattr(p, "PROJECT", False)]

    findings: list[Finding] = []
    parsed: dict[str, tuple[ast.AST, list[str]]] = {}
    supp: dict[str, Suppressions] = {}
    for path in walk_files(roots):
        tree, lines, err = parse_file(path)
        supp[path] = Suppressions(path, lines)
        findings.extend(supp[path].errors)
        if err is not None:
            findings.append(err)
            continue
        parsed[path] = (tree, lines)
        rel = path.replace(os.sep, "/")
        for p in file_passes:
            if p.applies(rel):
                findings.extend(p.check(path, tree, lines))
    for p in project_passes:
        findings.extend(p.check_project(parsed))

    for f in findings:
        if f.pass_id == "bad-suppression":
            continue  # meta-findings are never suppressable
        s = supp.get(f.path)
        reason = s.match(f) if s is not None else None
        if reason is not None:
            f.suppressed = True
            f.reason = reason
    return findings
