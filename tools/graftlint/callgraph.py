"""Whole-program def/call index for project-wide graftlint passes.

The per-file passes answer "is this line wrong on its own?"; the
concurrency passes need to answer "what locks are held when this
function is *reached*?" and "what does this thread target *transitively*
touch?" — questions that cross file boundaries.  This module builds a
module-qualified, class-method-aware index of every function definition
in the linted tree plus a conservative call-edge resolver, so a pass can
walk interprocedural paths without re-deriving scoping rules.

Resolution is deliberately *under*-approximate: an edge is only created
when the callee can be named with confidence —

* ``self.m()`` / ``cls.m()``      → method ``m`` on the enclosing class
  or a project base class (MRO approximated as depth-first base order);
* ``f()``                         → a function nested in the caller, a
  module-level function, a class constructor (``__init__``), or an
  imported function (``from x import f`` / relative imports resolved);
* ``mod.f()`` / ``pkg.mod.f()``   → a function or constructor in the
  imported module;
* ``Class.m()``                   → the method (unbound call);
* ``self._attr.m()``              → ``D.m`` when some method of the
  class assigns ``self._attr = D(...)`` for a project class ``D``;
* ``v.m()``                       → ``D.m`` when the caller assigns
  ``v = D(...)`` earlier in the same function.

Everything else (dynamic dispatch, stdlib, third-party) resolves to
nothing and simply truncates the walk — passes built on this graph
report *witnessed* paths, never guessed ones.
"""

from __future__ import annotations

import ast
import os


class FuncInfo:
    """One function/method definition."""

    __slots__ = (
        "qualname", "module", "cls", "name", "path", "lineno", "node",
        "parent", "nested",
    )

    def __init__(self, qualname, module, cls, name, path, lineno, node, parent):
        self.qualname = qualname  # "module:Class.method" / "module:func"
        self.module = module
        self.cls = cls  # ClassInfo | None
        self.name = name
        self.path = path
        self.lineno = lineno
        self.node = node
        self.parent = parent  # enclosing FuncInfo | None
        self.nested: dict[str, "FuncInfo"] = {}

    def __repr__(self):  # pragma: no cover - debug aid
        return f"<FuncInfo {self.qualname}>"


class ClassInfo:
    """One class definition: methods, bases, and ``self.attr = <Call>``
    assignments (the raw material for attribute-type and lock-field
    inference)."""

    __slots__ = (
        "qualname", "module", "name", "path", "lineno", "bases", "methods",
        "attr_assigns", "attr_types",
    )

    def __init__(self, qualname, module, name, path, lineno, bases):
        self.qualname = qualname  # "module:Class"
        self.module = module
        self.name = name
        self.path = path
        self.lineno = lineno
        self.bases = bases  # dotted base expressions, unresolved
        self.methods: dict[str, FuncInfo] = {}
        # attr -> (ast.Call value, lineno) for every `self.attr = X(...)`
        self.attr_assigns: dict[str, tuple[ast.Call, int]] = {}
        self.attr_types: dict[str, "ClassInfo"] = {}  # filled post-link

    def __repr__(self):  # pragma: no cover - debug aid
        return f"<ClassInfo {self.qualname}>"


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def walk_no_nested(body) -> "list[ast.AST]":
    """Every node lexically in ``body`` without descending into nested
    function/class definitions."""
    out: list[ast.AST] = []
    stack: list[ast.AST] = list(body) if isinstance(body, list) else [body]
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


class CallGraph:
    """Project-wide def/call index over graftlint's parsed-file dict."""

    def __init__(self, files: dict, root: str | None = None):
        """``files``: {path: (ast.Module, lines)} as engine.run collects.
        ``root``: directory module names are relative to; defaults to the
        common ancestor of every file (so the bundled corpus mini-trees
        index exactly like the real tree)."""
        paths = sorted(files)
        if root is None and paths:
            dirs = {os.path.dirname(os.path.abspath(p)) or "." for p in paths}
            root = os.path.commonpath(list(dirs)) if dirs else "."
        self.root = root or "."
        self.files = files
        self.functions: dict[str, FuncInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.module_path: dict[str, str] = {}
        self.module_tree: dict[str, ast.AST] = {}
        # module -> {local alias: dotted target ("a.b" / "a.b.name")}
        self.imports: dict[str, dict[str, str]] = {}
        # module -> {name: FuncInfo|ClassInfo} top-level scope
        self.scope: dict[str, dict[str, object]] = {}
        self._callee_cache: dict[str, list] = {}
        for path in paths:
            tree, _lines = files[path]
            self._index_module(path, tree)
        self._link_attr_types()

    # -- indexing ------------------------------------------------------------

    def module_name(self, path: str) -> str:
        rel = os.path.relpath(os.path.abspath(path), self.root)
        rel = rel.replace(os.sep, "/")
        if rel.endswith(".py"):
            rel = rel[:-3]
        if rel.endswith("/__init__"):
            rel = rel[: -len("/__init__")]
        return rel.replace("/", ".")

    def _index_module(self, path: str, tree: ast.AST) -> None:
        module = self.module_name(path)
        self.module_path[module] = path
        self.module_tree[module] = tree
        imports: dict[str, str] = {}
        scope: dict[str, object] = {}
        self.imports[module] = imports
        self.scope[module] = scope
        pkg = module.rsplit(".", 1)[0] if "." in module else ""

        for node in tree.body:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        imports[alias.asname] = alias.name
                    else:
                        # `import a.b` binds `a`; resolve chains lazily
                        imports[alias.name.split(".")[0]] = alias.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    up = pkg
                    for _ in range(node.level - 1):
                        up = up.rsplit(".", 1)[0] if "." in up else ""
                    base = f"{up}.{base}".strip(".") if base else up
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    imports[local] = f"{base}.{alias.name}" if base else alias.name
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = self._add_func(module, None, None, node, path)
                scope[node.name] = fi
            elif isinstance(node, ast.ClassDef):
                ci = self._add_class(module, node, path)
                scope[node.name] = ci

    def _add_func(self, module, cls, parent, node, path) -> FuncInfo:
        if parent is not None:
            qual = f"{parent.qualname}.{node.name}"
        elif cls is not None:
            qual = f"{module}:{cls.name}.{node.name}"
        else:
            qual = f"{module}:{node.name}"
        fi = FuncInfo(qual, module, cls, node.name, path, node.lineno, node, parent)
        self.functions[qual] = fi
        if parent is not None:
            parent.nested[node.name] = fi
        # index nested defs (thread targets are often local closures)
        self._index_nested(module, cls, fi, node.body, path)
        return fi

    def _index_nested(self, module, cls, parent, body, path) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_func(module, cls, parent, node, path)
            elif isinstance(node, (ast.If, ast.For, ast.While, ast.With, ast.Try)):
                for field in ("body", "orelse", "finalbody"):
                    self._index_nested(
                        module, cls, parent, getattr(node, field, []) or [], path
                    )
                for h in getattr(node, "handlers", []) or []:
                    self._index_nested(module, cls, parent, h.body, path)

    def _add_class(self, module, node, path) -> ClassInfo:
        bases = [b for b in (_dotted(x) for x in node.bases) if b]
        ci = ClassInfo(
            f"{module}:{node.name}", module, node.name, path, node.lineno, bases
        )
        self.classes[ci.qualname] = ci
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = self._add_func(module, ci, None, item, path)
                ci.methods[item.name] = fi
                for sub in walk_no_nested(item.body):
                    if (
                        isinstance(sub, ast.Assign)
                        and len(sub.targets) == 1
                        and isinstance(sub.targets[0], ast.Attribute)
                        and isinstance(sub.targets[0].value, ast.Name)
                        and sub.targets[0].value.id == "self"
                        and isinstance(sub.value, ast.Call)
                    ):
                        attr = sub.targets[0].attr
                        ci.attr_assigns.setdefault(attr, (sub.value, sub.lineno))
        return ci

    def _link_attr_types(self) -> None:
        for ci in self.classes.values():
            for attr, (call, _ln) in ci.attr_assigns.items():
                target = self._resolve_scope_name(ci.module, _dotted(call.func))
                if isinstance(target, ClassInfo):
                    ci.attr_types[attr] = target

    # -- name resolution -----------------------------------------------------

    def _resolve_scope_name(self, module: str, dotted: str | None):
        """A dotted name in ``module``'s top-level scope → FuncInfo /
        ClassInfo / module-name string / None."""
        if not dotted:
            return None
        head, _, rest = dotted.partition(".")
        target = self.scope.get(module, {}).get(head)
        if target is None:
            imp = self.imports.get(module, {}).get(head)
            if imp is None:
                return None
            return self._resolve_imported(imp + ("." + rest if rest else ""))
        if not rest:
            return target
        if isinstance(target, ClassInfo) and "." not in rest:
            return target.methods.get(rest)
        return None

    def _resolve_imported(self, dotted: str):
        """Fully-dotted import target → FuncInfo / ClassInfo / module str."""
        if dotted in self.module_path:
            return dotted
        if "." in dotted:
            mod, _, name = dotted.rpartition(".")
            # the prefix may itself be a package path of indexed modules
            if mod in self.module_path:
                obj = self.scope.get(mod, {}).get(name)
                if obj is not None:
                    return obj
                return None
            # one more level: a.b.Class.method
            if "." in mod:
                mod2, _, cls = mod.rpartition(".")
                if mod2 in self.module_path:
                    obj = self.scope.get(mod2, {}).get(cls)
                    if isinstance(obj, ClassInfo):
                        return obj.methods.get(name)
        return None

    def resolve_base(self, ci: ClassInfo, dotted: str) -> ClassInfo | None:
        obj = self._resolve_scope_name(ci.module, dotted)
        return obj if isinstance(obj, ClassInfo) else None

    def mro(self, ci: ClassInfo) -> list[ClassInfo]:
        """Depth-first base order (approximate MRO; good enough for
        single-inheritance project code)."""
        out, seen, stack = [], set(), [ci]
        while stack:
            c = stack.pop(0)
            if c.qualname in seen:
                continue
            seen.add(c.qualname)
            out.append(c)
            bases = [self.resolve_base(c, b) for b in c.bases]
            stack = [b for b in bases if b is not None] + stack
        return out

    def lookup_method(self, ci: ClassInfo, name: str) -> FuncInfo | None:
        for c in self.mro(ci):
            if name in c.methods:
                return c.methods[name]
        return None

    def attr_type(self, ci: ClassInfo, attr: str) -> ClassInfo | None:
        for c in self.mro(ci):
            if attr in c.attr_types:
                return c.attr_types[attr]
        return None

    def _local_var_types(self, fi: FuncInfo) -> dict[str, ClassInfo]:
        """{var: ClassInfo} for ``v = D(...)`` assignments in ``fi``."""
        out: dict[str, ClassInfo] = {}
        for node in walk_no_nested(fi.node.body):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                target = self._resolve_scope_name(
                    fi.module, _dotted(node.value.func)
                )
                if isinstance(target, ClassInfo):
                    out[node.targets[0].id] = target
        return out

    def resolve_callable(self, fi: FuncInfo | None, module: str,
                         expr: ast.AST) -> FuncInfo | None:
        """Resolve a callable *expression* (a Thread target, a submit
        arg, or a Call's ``func``) to its FuncInfo, or None."""
        cls = fi.cls if fi is not None else None
        if isinstance(expr, ast.Name):
            name = expr.id
            # innermost enclosing function's nested defs first
            scope_fi = fi
            while scope_fi is not None:
                if name in scope_fi.nested:
                    return scope_fi.nested[name]
                scope_fi = scope_fi.parent
            obj = self._resolve_scope_name(module, name)
            if isinstance(obj, FuncInfo):
                return obj
            if isinstance(obj, ClassInfo):
                return self.lookup_method(obj, "__init__")
            return None
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            recv = expr.value
            if isinstance(recv, ast.Name):
                if recv.id in ("self", "cls") and cls is not None:
                    return self.lookup_method(cls, attr)
                obj = self._resolve_scope_name(module, recv.id)
                if isinstance(obj, ClassInfo):
                    return self.lookup_method(obj, attr)
                if isinstance(obj, str):  # imported module
                    sub = self.scope.get(obj, {}).get(attr)
                    if isinstance(sub, FuncInfo):
                        return sub
                    if isinstance(sub, ClassInfo):
                        return self.lookup_method(sub, "__init__")
                    return None
                if fi is not None:
                    lt = self._local_var_types(fi).get(recv.id)
                    if lt is not None:
                        return self.lookup_method(lt, attr)
                return None
            if (
                isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id in ("self", "cls")
                and cls is not None
            ):
                # self._attr.m() through the inferred attribute type
                at = self.attr_type(cls, recv.attr)
                if at is not None:
                    return self.lookup_method(at, attr)
                return None
            d = _dotted(recv)
            if d is not None:
                # pkg.mod.f() through the import map
                head = d.split(".")[0]
                imp = self.imports.get(module, {}).get(head)
                if imp is not None:
                    full = d.replace(head, imp, 1) + "." + attr
                    obj = self._resolve_imported(full)
                    if isinstance(obj, FuncInfo):
                        return obj
                    if isinstance(obj, ClassInfo):
                        return self.lookup_method(obj, "__init__")
            return None
        return None

    # -- edges ---------------------------------------------------------------

    def callees(self, fi: FuncInfo) -> list:
        """[(ast.Call, FuncInfo)] for every resolvable call lexically in
        ``fi`` (nested defs excluded — they run in their own context)."""
        cached = self._callee_cache.get(fi.qualname)
        if cached is not None:
            return cached
        out = []
        for node in walk_no_nested(fi.node.body):
            if isinstance(node, ast.Call):
                target = self.resolve_callable(fi, fi.module, node.func)
                if target is not None:
                    out.append((node, target))
        out.sort(key=lambda t: (t[0].lineno, t[0].col_offset, t[1].qualname))
        self._callee_cache[fi.qualname] = out
        return out

    def reachable(self, start: FuncInfo) -> dict[str, list]:
        """{qualname: call-site chain [(path, line), ...]} for every
        function reachable from ``start`` (BFS; first/shortest chain
        kept, deterministic)."""
        seen: dict[str, list] = {start.qualname: []}
        frontier = [start]
        while frontier:
            nxt: list[FuncInfo] = []
            for fi in frontier:
                chain = seen[fi.qualname]
                for call, target in self.callees(fi):
                    if target.qualname in seen:
                        continue
                    seen[target.qualname] = chain + [(fi.path, call.lineno)]
                    nxt.append(target)
            frontier = nxt
        return seen

    def enclosing_functions(self, module: str):
        """Every FuncInfo of ``module`` (methods, functions, nested)."""
        return sorted(
            (f for f in self.functions.values() if f.module == module),
            key=lambda f: (f.lineno, f.qualname),
        )
