"""CI smoke check for the concurrency correctness suite
(docs/robustness.md "Concurrency discipline").

Boots one real NodeServer with the runtime lockdep witness installed in
``raise`` mode — every project lock allocation is wrapped before server
modules load — then drives a concurrent mixed read/ingest burst over
actual HTTP so handler threads, the batcher dispatcher, the ingest
uploader, and the residency manager all interleave. Asserts:

* the burst completes with zero errors and **zero lock-order
  inversions** recorded (an inversion would have raised at its
  acquisition site inside a server thread and failed the request);
* the witness actually saw the serving plane (acquisitions and order
  edges were recorded, not a silent no-op);
* **static↔runtime cross-check**: runtime order edges are mapped onto
  the static lock-graph identities through their shared allocation
  sites, and the merged static+runtime acquisition-order graph is still
  acyclic — a runtime edge that reverses a static edge (or vice versa)
  is a deadlock neither side could prove alone.

Exit status 0 on success; any assertion/exception fails the CI step.
Run as ``python -m tools.smoke_lockwitness``.
"""

from __future__ import annotations

import json
import random
import sys
import threading
import urllib.request

N_FIELDS = 6
WRITER_THREADS = 4
READER_THREADS = 6
OPS_PER_THREAD = 30


def _get(uri: str) -> bytes:
    return urllib.request.urlopen(uri, timeout=10).read()


def _post(uri: str, body: bytes, ctype: str = "text/plain") -> bytes:
    req = urllib.request.Request(
        uri, data=body, headers={"Content-Type": ctype}, method="POST"
    )
    return urllib.request.urlopen(req, timeout=10).read()


def _static_lock_graph():
    """(site -> static lock id, static edge set) from the lock-graph
    pass, over the same tree the witness scopes to."""
    import os

    from tools.graftlint import engine
    from tools.graftlint.callgraph import CallGraph, _dotted
    from tools.graftlint.passes import lock_graph

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    roots = [os.path.join(repo, d) for d in ("pilosa_tpu", "tools")]
    parsed = {}
    for path in engine.walk_files(roots):
        tree, lines, err = engine.parse_file(path)
        if err is None:
            parsed[path] = (tree, lines)
    graph = CallGraph(parsed, root=repo)
    an = lock_graph._Analysis(parsed, graph)

    sites: dict[str, str] = {}
    for ci in graph.classes.values():
        for attr, (call, _ln) in ci.attr_assigns.items():
            lid = an.class_locks.get((ci.qualname, attr))
            if lid is not None:
                rel = lock_graph._rel(ci.path, repo)
                sites[f"{rel}:{call.lineno}"] = lid
    import ast

    for module, tree in graph.module_tree.items():
        for node in tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                lid = an.module_locks.get((module, node.targets[0].id))
                if lid is not None:
                    rel = lock_graph._rel(graph.module_path[module], repo)
                    sites[f"{rel}:{node.value.lineno}"] = lid
    return sites, set(an.edges()), lock_graph._cycles


def main() -> int:
    # install BEFORE server modules import, so their module-level locks
    # are allocated through the patched factories
    from pilosa_tpu.testing import lockwitness

    lockwitness.install(mode="raise")
    lockwitness.reset()

    from pilosa_tpu.server.node import NodeServer

    node = NodeServer(port=0, batch_window=0.003, batch_max_size=32)
    node.start()
    try:
        base = node.uri
        _post(f"{base}/index/lw", b"{}", "application/json")
        for fi in range(N_FIELDS):
            _post(
                f"{base}/index/lw/field/f{fi}",
                b'{"options": {}}',
                "application/json",
            )
        # seed rows so reads have something to intersect
        seed = "".join(
            f"Set({col}, f{fi}={row})"
            for fi in range(N_FIELDS)
            for row in (1, 2)
            for col in range(0, 64, 4)
        )
        _post(f"{base}/index/lw/query", seed.encode())

        errors: list[BaseException] = []

        def writer(seedn: int) -> None:
            r = random.Random(seedn)
            try:
                for _ in range(OPS_PER_THREAD):
                    fi = r.randrange(N_FIELDS)
                    ops = "".join(
                        f"Set({r.randrange(512)}, f{fi}={r.choice((1, 2))})"
                        for _ in range(8)
                    )
                    resp = json.loads(
                        _post(f"{base}/index/lw/query", ops.encode())
                    )
                    assert "results" in resp, resp
            except BaseException as e:  # surfaced after join
                errors.append(e)

        def reader(seedn: int) -> None:
            r = random.Random(seedn)
            try:
                for _ in range(OPS_PER_THREAD):
                    fi = r.randrange(N_FIELDS)
                    q = r.choice(
                        (
                            f"Count(Row(f{fi}=1))",
                            f"Count(Intersect(Row(f{fi}=1), Row(f{fi}=2)))",
                            f"TopN(f{fi}, n=2)",
                        )
                    )
                    resp = json.loads(
                        _post(f"{base}/index/lw/query", q.encode())
                    )
                    assert "results" in resp, resp
            except BaseException as e:
                errors.append(e)

        threads = [
            threading.Thread(target=writer, args=(100 + i,), daemon=True)
            for i in range(WRITER_THREADS)
        ] + [
            threading.Thread(target=reader, args=(200 + i,), daemon=True)
            for i in range(READER_THREADS)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "burst thread hung"
        assert not errors, errors[:3]
        assert node.api.ingest.uploader.flush(10.0), "uploader never idled"

        # zero inversions across the whole burst (raise mode would also
        # have failed the owning request, but a worker thread may have
        # swallowed the exception — findings() is the ground truth)
        assert lockwitness.findings() == [], lockwitness.findings()
        stats = lockwitness.stats()
        assert stats["witnessedAcquires"] > 100, stats
        assert stats["edges"] > 0, stats
        runtime_edges = lockwitness.order_graph()

        # static <-> runtime cross-check: merge both order graphs over
        # the shared allocation-site identity; a cycle in the union is a
        # deadlock neither half could prove alone
        sites, static_edges, cycles = _static_lock_graph()
        mapped = 0
        merged: dict[tuple, tuple] = {
            e: (("static", 0), ()) for e in static_edges
        }
        for (a, b), _w in runtime_edges.items():
            la, lb = sites.get(a), sites.get(b)
            if la is None or lb is None or la == lb:
                continue
            mapped += 1
            merged.setdefault((la, lb), (("runtime", 0), ()))
        cyc = cycles(merged)
        assert cyc == [], f"static+runtime order graph has cycles: {cyc}"

        print(
            "smoke_lockwitness OK: "
            f"acquires={stats['witnessedAcquires']} "
            f"runtimeEdges={len(runtime_edges)} "
            f"mappedToStatic={mapped} staticEdges={len(static_edges)} "
            f"inversions=0"
        )
        return 0
    finally:
        node.stop()


if __name__ == "__main__":
    sys.exit(main())
