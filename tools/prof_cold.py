"""Profile the cold sequential Count(Intersect) path exactly as bench.py
measures it (full TPU-size index, host latency tier), on the CPU
platform — the host tier never touches the device, so the numbers
transfer to the driver's bench run."""
import os
import sys
import time

import numpy as np

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.exec.executor import Executor

S, R, W = 160, 64, 32768
rng = np.random.default_rng(3)
B = 64
ras = rng.integers(0, R, size=B).astype(np.int64)
rbs = rng.integers(0, R, size=B).astype(np.int64)

h = Holder(n_words=W)
idx = h.create_index("seq")
f = idx.create_field("f")
v = f.create_view_if_not_exists(VIEW_STANDARD)
seq_rng = np.random.default_rng(13)
t0 = time.perf_counter()
for s in range(S):
    words = seq_rng.integers(0, 2**32, size=(R, W), dtype=np.uint32) & \
        seq_rng.integers(0, 2**32, size=(R, W), dtype=np.uint32)
    frag = v.create_fragment_if_not_exists(s)
    for r in range(R):
        frag.set_row_words(r, words[r])
print(f"setup: {time.perf_counter()-t0:.1f}s")

ex = Executor(h)
ex._PAIR_SINGLE_WARM = 10**9
q0 = f"Count(Intersect(Row(f={int(ras[0])}), Row(f={int(rbs[0])})))"
ex.execute("seq", q0)

from pilosa_tpu.ops import _hostops
print("native hostops:", _hostops.load() is not None)

n_seq = 30
t0 = time.perf_counter()
for i in range(n_seq):
    ex.execute(
        "seq",
        f"Count(Intersect(Row(f={int(ras[i % B])}), Row(f={int(rbs[i % B])})))",
    )
dt = time.perf_counter() - t0
print(f"cold execute: {dt/n_seq*1e3:.2f} ms/q  ({n_seq/dt:.1f} qps)")

# phase breakdown -------------------------------------------------------
from pilosa_tpu.pql.parser import parse

t0 = time.perf_counter()
for i in range(n_seq):
    parse(f"Count(Intersect(Row(f={int(ras[i % B])}), Row(f={int(rbs[i % B])})))")
print(f"parse only:   {(time.perf_counter()-t0)/n_seq*1e3:.2f} ms/q")

shard_list = list(range(S))
view = idx.field("f").view(VIEW_STANDARD)
t0 = time.perf_counter()
for i in range(n_seq):
    ex._host_pair_count(view, int(ras[i % B]), int(rbs[i % B]), "intersect", shard_list)
print(f"host_pair_count only: {(time.perf_counter()-t0)/n_seq*1e3:.2f} ms/q")

# raw native call, addresses precomputed once
frags = [view.fragment(s) for s in shard_list]
n_words = frags[0].n_words
t0 = time.perf_counter()
for i in range(n_seq):
    ra, rb = int(ras[i % B]), int(rbs[i % B])
    bases = np.array([f_._host.__array_interface__["data"][0] for f_ in frags], dtype=np.uint64)
    sa = np.array([f_._slot_of[ra] for f_ in frags], dtype=np.uint64)
    sb = np.array([f_._slot_of[rb] for f_ in frags], dtype=np.uint64)
    stride = np.uint64(n_words * 4)
    _hostops.pair_count_addrs(bases + sa * stride, bases + sb * stride, n_words, "intersect")
print(f"raw native:   {(time.perf_counter()-t0)/n_seq*1e3:.2f} ms/q")

# numpy baseline as bench.py does it (cache-hot, scaled from 10 shards)
sub = np.stack([frags[s]._host[frags[s]._slot_of[0]] for s in range(10)])
suba = np.empty((10, n_words), dtype=np.uint32)
subb = np.empty((10, n_words), dtype=np.uint32)
qa, qb = int(ras[0]), int(rbs[0])
for s in range(10):
    suba[s] = frags[s]._host[frags[s]._slot_of[qa]]
    subb[s] = frags[s]._host[frags[s]._slot_of[qb]]
times = []
for _ in range(5):
    t0 = time.perf_counter()
    int(np.bitwise_count(suba & subb).sum())
    times.append(time.perf_counter() - t0)
print(f"numpy baseline (scaled x16, best of 5): {min(times)*16*1e3:.2f} ms/q")

if "--cprofile" in sys.argv:
    import cProfile
    import pstats

    pr = cProfile.Profile()
    pr.enable()
    for i in range(n_seq):
        ex.execute(
            "seq",
            f"Count(Intersect(Row(f={int(ras[i % B])}), Row(f={int(rbs[i % B])})))",
        )
    pr.disable()
    pstats.Stats(pr).sort_stats("cumulative").print_stats(25)
