"""Online-resize smoke: drive zipfian traffic at a small cluster while a
node is added and then removed, and assert the resize stayed invisible
to clients.

Asserts:
  * the resize stage completed its hook (add node mid-traffic, then
    remove it) with no hook error
  * the stage's availability verdict is green — membership changes must
    not open a cluster-wide error window (the old RESIZING gate would
    have failed every request for the duration)
  * /debug/events carries the full resize timeline: two resize-start /
    resize-commit pairs (grow + shrink), per-fragment migrate-fragment
    records, epoch-flip broadcasts, and no resize-abort
  * the error budget stayed green: no burn-rate alert fired in any SLO
    class during the run (latency objectives are NOT asserted — short
    cold-start runs legitimately blow them, see tools/loadharness.py)
  * the emitted report validates against pilosa-slo-report/v1

Run: python -m tools.smoke_resize      (CI: resize smoke step)
"""

from __future__ import annotations

import sys

from pilosa_tpu.loadgen import (
    WorkloadConfig,
    run_harness,
    validate_report,
)
from tools.loadharness import SHORT_BURN_RULES, resize_hook, resize_stage

# Small shards (128 words = 4096 columns) so the zipfian key space spans
# ~10 shard groups and the add/remove resizes are guaranteed to migrate
# fragments rather than no-op on a single-shard layout.
N_WORDS = 128
N_COLS = 40_000


def main() -> int:
    config = WorkloadConfig(seed=2026, n_cols=N_COLS)
    stage = resize_stage(duration=2.5, rate=80.0, workers=4)
    report = run_harness(
        config,
        [stage],
        nodes=2,
        cluster_kwargs={
            "replica_n": 2,
            "n_words": N_WORDS,
            "slo_burn_rules": SHORT_BURN_RULES,
            "slo_slot_seconds": 1.0,
            "slo_latency_window": 60.0,
        },
        preload_bits=2048,
        stage_hooks={"resize": resize_hook},
    )
    validate_report(report)

    st = report["stages"][0]
    assert st["hookRan"], "resize hook never started"
    assert st["hookError"] is None, f"resize hook failed: {st['hookError']}"
    assert st["availabilityOk"], (
        f"resize stage availability {st['availability']:.4f} below floor "
        f"({st['okOps']}/{st['ops']} ok, {st['clientErrors']} client errors)"
    )

    # resize timeline from the coordinator's event journal (rides in the
    # report so SLO_r*.json is self-contained evidence)
    types = [e["type"] for e in report["events"]]
    assert types.count("resize-start") == 2, types
    assert types.count("resize-commit") == 2, types
    assert "migrate-fragment" in types, "no fragment migrated during resize"
    assert "epoch-flip" in types, "no epoch flip broadcast during resize"
    assert "resize-abort" not in types, "a resize aborted mid-smoke"
    # ordering: first start precedes first commit precedes second start
    assert types.index("resize-start") < types.index("resize-commit")
    assert types.index("resize-commit") < _rindex(types, "resize-start")

    # green error budget: no burn-rate alert fired in any class
    for name, cls in report["serverSLO"]["classes"].items():
        firing = [r for r, on in (cls.get("alerts") or {}).items() if on]
        assert not firing, f"burn alert(s) {firing} fired for class {name}"

    print(
        f"resize smoke OK: availability={st['availability']:.4f} "
        f"migrations={types.count('migrate-fragment')} "
        f"flips={types.count('epoch-flip')}"
    )
    return 0


def _rindex(seq: list, value) -> int:
    return len(seq) - 1 - seq[::-1].index(value)


if __name__ == "__main__":
    sys.exit(main())
