"""Post-change TPU validation: run after kernel/executor changes when
the chip is reachable (``python tools/tpu_recheck.py``).

1. The retiled Pallas row scans must COMPILE on the real chip
   (PILOSA_TPU_PALLAS=1 path) and match the XLA scan.
2. The executor's gram batch path must answer correctly at serving shape.
3. Quick pipelined rates for the serving kernels.
"""

from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from pilosa_tpu.ops import kernels


def main() -> None:
    dev = jax.devices()[0]
    print(f"device: {dev} ({dev.platform})")
    S, R, W = 160, 64, 32768
    key = jax.random.PRNGKey(7)
    k1, k2 = jax.random.split(key)
    bits = jax.random.bits(k1, (S, R, W), dtype=jnp.uint32) & jax.random.bits(
        k2, (S, R, W), dtype=jnp.uint32
    )
    np.asarray(bits[0, 0, 0])

    # 1. Pallas row scans compile + match
    want = np.asarray(kernels.row_counts_per_shard_xla(bits))
    try:
        got = np.asarray(kernels.row_counts_per_shard_pallas(bits))
        assert (got == want).all(), "pallas row scan MISMATCH"
        print("pallas row scan: compiles, matches XLA")
    except Exception as e:
        print(f"pallas row scan FAILED: {type(e).__name__}: {str(e)[:200]}")
    filt = jax.random.bits(k2, (S, W), dtype=jnp.uint32)
    try:
        got = np.asarray(kernels.masked_row_counts_pallas(bits, filt))
        wantm = np.asarray(kernels.masked_row_counts_xla(bits, filt))
        assert (got == wantm).all(), "pallas masked scan MISMATCH"
        print("pallas masked scan: compiles, matches XLA")
    except Exception as e:
        print(f"pallas masked scan FAILED: {type(e).__name__}: {str(e)[:200]}")

    # 2. gram correctness at serving shape
    g = kernels.pair_gram(bits, list(range(R)))
    ra, rb = 3, 7
    want_pair = int(np.bitwise_count(np.asarray(bits[:, ra] & bits[:, rb])).sum())
    assert int(g[ra, rb]) == want_pair, "gram MISMATCH"
    print("gram: exact at serving shape")

    # 3. pipelined rates
    gram_salted = jax.jit(lambda b, s: kernels.gram_matrix_xla(b ^ s))
    np.asarray(gram_salted(bits, jnp.uint32(9)))
    t0 = time.perf_counter()
    outs = [gram_salted(bits, jnp.uint32(i)) for i in range(4)]
    np.asarray(outs[-1])
    t = (time.perf_counter() - t0) / 4
    print(f"gram: {t*1e3:.1f} ms/launch ({R*R/t:.0f} pairs/s)")
    scan_salted = jax.jit(lambda b, s: kernels.row_counts_per_shard_xla(b ^ s))
    np.asarray(scan_salted(bits, jnp.uint32(9)))
    t0 = time.perf_counter()
    outs = [scan_salted(bits, jnp.uint32(i)) for i in range(6)]
    np.asarray(outs[-1])
    t = (time.perf_counter() - t0) / 6
    print(f"xla row scan: {t*1e3:.1f} ms ({S*R*W*4/t/1e9:.0f} GB/s)")


if __name__ == "__main__":
    main()
