"""CI smoke for the retrospective metrics plane (obs/history.py).

Boots a REAL 2-node in-process cluster with a fast history cadence and
asserts, end to end over HTTP:

* the ring TSDB accumulates: after a query burst, ``/debug/history``
  serves ``slo.*`` series with an advancing ``nextSeq``, ``?since=``
  cursors resume gap-honestly, and ``?step=`` downsamples;
* ``GET /debug`` lists the registered debug endpoints (history and
  incidents included);
* a fault-injected latency regression — a ``slow`` network fault on the
  coordinator's fan-out legs — makes the EWMA latency-regression
  detector fire EXACTLY ONE ``trend`` incident for the episode, whose
  bundle attaches the pre-incident series window;
* ``?cluster=true`` merges every node's series into one wall-clock-
  aligned timeline with per-node attribution preserved.

Exit status 0 on success; any assertion/exception fails the CI step.
Run as ``python -m tools.smoke_history``.
"""

from __future__ import annotations

import json
import sys
import time
import urllib.request


def _get(uri: str) -> bytes:
    return urllib.request.urlopen(uri, timeout=10).read()


def _post(uri: str, body: bytes, timeout: float = 10.0) -> bytes:
    req = urllib.request.Request(
        uri, data=body, headers={"Content-Type": "text/plain"},
        method="POST",
    )
    return urllib.request.urlopen(req, timeout=timeout).read()


# fast knobs: ~20 samples/s, tiny rings, short warmup — the whole smoke
# runs in a few seconds while exercising the same code paths as the 1 s
# production cadence
CADENCE = 0.05
CLUSTER_KNOBS = dict(
    history_cadence=CADENCE,
    history_tiers="200@1,50@10",
    history_warmup=8,
    history_trips=3,
    history_latency_min_ms=30.0,
    # HTTP fan-out plane: the slow fault hooks the internal client, and
    # mesh dispatch would bypass it
    mesh_dispatch=False,
    slo_slot_seconds=0.5,
    slo_latency_window=10.0,
)


def main() -> int:
    from pilosa_tpu.testing.cluster import InProcessCluster

    with InProcessCluster(2, **CLUSTER_KNOBS) as cluster:
        base = cluster.nodes[0].uri
        cluster.create_index("hsmoke")
        cluster.create_field("hsmoke", "f")
        # span 4 shards so the coordinator's Count genuinely fans out to
        # the peer (the slow fault lives on the internal-client path);
        # with 2 nodes the hash ring places shards on both
        shard_width = cluster.nodes[0].api.holder.n_words * 32
        writes = " ".join(
            f"Set({s * shard_width + c}, f={r})"
            for r in range(2)
            for s in range(4)
            for c in (1, 2)
        )
        _post(f"{base}/index/hsmoke/query", writes.encode(), timeout=120)

        def burst(n: int, pause: float = 0.0) -> None:
            for _ in range(n):
                _post(
                    f"{base}/index/hsmoke/query",
                    b"Count(Intersect(Row(f=0), Row(f=1)))",
                )
                if pause:
                    time.sleep(pause)

        # -- /debug index: discoverability ------------------------------
        idx = json.loads(_get(f"{base}/debug"))
        paths = {e["path"] for e in idx["endpoints"]}
        assert "/debug/history" in paths and "/debug/incidents" in paths, (
            paths
        )
        assert all(e.get("desc") for e in idx["endpoints"]), idx

        # -- series accumulate under a burst ----------------------------
        burst(30, pause=0.01)
        deadline = time.monotonic() + 15.0
        snap = {}
        while time.monotonic() < deadline:
            snap = json.loads(_get(f"{base}/debug/history"))
            slo_series = [
                s for s in snap.get("series", {}) if s.startswith("slo.")
            ]
            if snap.get("nextSeq", 0) >= 20 and slo_series:
                break
            burst(5)
            time.sleep(CADENCE)
        assert snap.get("nextSeq", 0) >= 20, snap.get("nextSeq")
        p99_names = [
            s for s in snap["series"] if s.endswith(".p99_ms")
        ]
        assert p99_names, sorted(snap["series"])

        # -- gap-honest cursors -----------------------------------------
        # a cursor at the head resumes without rewinding (the sampler is
        # live, so a few ticks may land between the two reads)
        cur = snap["nextSeq"]
        resumed = json.loads(_get(f"{base}/debug/history?since={cur}"))
        assert resumed["truncated"] is False, resumed
        assert resumed["nextSeq"] >= cur, (resumed["nextSeq"], cur)
        burst(5)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            resumed = json.loads(_get(f"{base}/debug/history?since={cur}"))
            if resumed["returned"] >= 1:
                break
            time.sleep(CADENCE * 4)
        assert resumed["returned"] >= 1, resumed["returned"]
        assert resumed["truncated"] is False, resumed
        # a cursor behind the ring must say so, not silently skip
        while True:
            snap = json.loads(_get(f"{base}/debug/history?limit=0"))
            if snap["firstSeq"] > 0:
                break
            burst(2)
            time.sleep(CADENCE * 10)
        stale = json.loads(_get(f"{base}/debug/history?since=0"))
        assert stale["truncated"] is True, stale["firstSeq"]

        # -- ?step= downsampling ----------------------------------------
        full = json.loads(_get(f"{base}/debug/history"))
        coarse = json.loads(
            _get(f"{base}/debug/history?step={CADENCE * 10}")
        )
        name = p99_names[0]
        if name in coarse["series"] and name in full["series"]:
            assert len(coarse["series"][name]) < len(
                full["series"][name]
            ), (len(coarse["series"][name]), len(full["series"][name]))

        # -- fault-injected latency regression => ONE trend incident ----
        # baseline: fast queries (fan-out legs answer in ~ms), until the
        # detector is warmed up for at least one class AND its EWMA has
        # decayed past the first-compile latency spike (a baseline still
        # chasing that spike would swallow the injected regression)
        deadline = time.monotonic() + 45.0
        warmed = []
        det = {"series": {}}
        while time.monotonic() < deadline:
            burst(5)
            time.sleep(CADENCE)
            det = json.loads(_get(f"{base}/debug/history"))["detectors"]
            warmed = [
                k for k, st in det["series"].items()
                if k.startswith("latency:")
                and st["n"] >= 8
                and st["baseline"] is not None
                and st["baseline"] <= 150.0
            ]
            if warmed:
                break
        assert warmed, det["series"]

        # regression: every coordinator->peer leg now stalls 1 s —
        # far past 2x any warm baseline the loop above admits
        cluster.inject_fault("slow", node=1, delay=1.0)
        deadline = time.monotonic() + 30.0
        trend = []
        while time.monotonic() < deadline and not trend:
            burst(3)
            time.sleep(CADENCE)
            incidents = json.loads(_get(f"{base}/debug/incidents"))
            trend = [
                i for i in incidents["incidents"]
                if (i.get("trigger") or {}).get("type") == "trend"
            ]
        assert len(trend) == 1, trend
        trig = trend[0]["trigger"]
        assert trig["detector"] == "latency-regression", trig
        assert trig["observed"] > trig["baseline"], trig

        # keep the regression burning: the episode latch must hold ONE
        # incident, not fire per tripping series
        burst(6)
        time.sleep(CADENCE * 10)
        incidents = json.loads(_get(f"{base}/debug/incidents"))
        trend = [
            i for i in incidents["incidents"]
            if (i.get("trigger") or {}).get("type") == "trend"
        ]
        assert len(trend) == 1, [i["trigger"] for i in trend]

        # the bundle attaches the pre-incident series window
        bundle = json.loads(
            _get(f"{base}/debug/incidents?id={trend[0]['id']}")
        )
        series = bundle.get("series") or {}
        assert series.get("series"), bundle.keys()
        assert trig["series"] in series["series"], (
            trig["series"], sorted(series["series"]),
        )
        assert series.get("preSeconds", 0) > 0, series.get("preSeconds")
        cluster.clear_faults()

        # -- cluster merge: wall-clock-aligned, per-node attribution ----
        merged = json.loads(
            _get(f"{base}/debug/history?cluster=true&step={CADENCE * 10}")
        )
        assert merged["cluster"] is True, merged
        assert len(merged["nodes"]) == 2, merged["nodes"]
        assert not merged["unreachable"], merged["unreachable"]
        step = merged["step"]
        per_node_names = set()
        for sname, by_node in merged["series"].items():
            for node_id, pts in by_node.items():
                per_node_names.add(node_id)
                for t, _v in pts:
                    # every point sits on the shared wall-clock grid
                    # (1e-3 tolerance: grid times are rounded to ms)
                    assert abs(t / step - round(t / step)) < 1e-3, (
                        sname, node_id, t, step,
                    )
        assert per_node_names == set(merged["nodes"]), (
            per_node_names, merged["nodes"],
        )
        # both nodes contribute their own slo series (each sampled its
        # own traffic: node0 served the burst, node1 the fan-out legs)
        multi = [
            s for s, by_node in merged["series"].items()
            if len(by_node) == 2
        ]
        assert multi, {
            s: sorted(b) for s, b in list(merged["series"].items())[:8]
        }

    print("history smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
