"""CI smoke check for the semantic result cache (docs/caching.md).

Boots one real NodeServer and drives it over actual HTTP through the
cache's whole life cycle:

* a repeated query **hits** (second arrival served from the cache,
  identical payload);
* a **targeted write invalidates precisely** — the written field's
  entry drops, a sibling field's entry keeps serving (hit count still
  climbs across the write);
* a hot unfiltered TopN **promotes** to a maintained view and reads
  back the correct post-write counts through in-place refresh instead
  of invalidation;
* the operator surfaces carry it: ``pilosa_rescache_*`` series in
  ``/metrics``, the ``rescache`` block in ``/debug/vars``, the
  ``rescache.lookup`` span under ``?profile=true``, and per-fragment
  ``version``/``epoch`` in ``/debug/fragments``.

Exit status 0 on success; any assertion/exception fails the CI step.
Run as ``python -m tools.smoke_rescache``.
"""

from __future__ import annotations

import json
import sys
import urllib.request


def _get(uri: str) -> bytes:
    return urllib.request.urlopen(uri, timeout=10).read()


def _post(uri: str, body: bytes, ctype: str = "text/plain") -> bytes:
    req = urllib.request.Request(
        uri, data=body, headers={"Content-Type": ctype}, method="POST"
    )
    return urllib.request.urlopen(req, timeout=10).read()


def main() -> int:
    from pilosa_tpu.server.node import NodeServer

    node = NodeServer(
        port=0,
        batch_window=0.002,
        batch_max_size=32,
        rescache_promote_hits=3,
    )
    node.start()
    try:
        base = node.uri
        _post(f"{base}/index/rc", b"{}", "application/json")
        for f in ("f", "g"):
            _post(
                f"{base}/index/rc/field/{f}",
                b'{"options": {}}',
                "application/json",
            )
        _post(
            f"{base}/index/rc/query",
            b"Set(1, f=1) Set(2, f=1) Set(3, f=2) Set(1, g=1) Set(4, g=1)",
        )

        def rc_vars() -> dict:
            return json.loads(_get(f"{base}/debug/vars"))["rescache"]

        def query(q: str, profile: bool = False) -> dict:
            suffix = "?profile=true" if profile else ""
            return json.loads(
                _post(f"{base}/index/rc/query{suffix}", q.encode())
            )

        # 1. repeat query -> hit, identical payload
        q_f = "Count(Row(f=1))"
        q_g = "Count(Row(g=1))"
        first = query(q_f)
        assert first["results"] == [2], first
        before = rc_vars()
        second = query(q_f)
        assert second == first, (first, second)
        after = rc_vars()
        assert after["hits"] == before["hits"] + 1, (before, after)

        # 2. targeted write -> precise invalidation: g's entry drops,
        # f's entry keeps serving
        query(q_g)  # seed g's entry
        before = rc_vars()
        _post(f"{base}/index/rc/query", b"Set(9, g=1)")
        assert query(q_g)["results"] == [3]  # fresh, not stale
        hit_floor = rc_vars()["hits"]
        assert query(q_f)["results"] == [2]  # f survived the g write
        after = rc_vars()
        assert after["invalidations"] > before["invalidations"], (before, after)
        assert after["hits"] > hit_floor - 1 and after["hits"] >= before["hits"] + 1, (
            before,
            after,
        )

        # 3. hot TopN promotes; a write refreshes it in place and the
        # readback carries the post-write counts
        for _ in range(5):
            query("TopN(f)")
        assert rc_vars()["promotions"] >= 1, rc_vars()
        _post(f"{base}/index/rc/query", b"Set(5, f=2) Set(6, f=2) Set(7, f=2)")
        top = query("TopN(f)")["results"][0]
        got = [(p["id"], p["count"]) for p in top]
        assert got == [(2, 4), (1, 2)], got
        snap = rc_vars()
        assert snap["maintainedHits"] >= 1 and snap["maintainedEntries"] >= 1, snap

        # 4. operator surfaces
        metrics = _get(f"{base}/metrics").decode()
        for series in (
            "pilosa_rescache_hits",
            "pilosa_rescache_misses",
            "pilosa_rescache_invalidations",
            "pilosa_rescache_promotions",
        ):
            assert series in metrics, f"{series} missing from /metrics"

        prof = query("Count(Row(g=1))", profile=True)
        names = json.dumps(prof.get("profile", {}))
        assert "rescache.lookup" in names, names[:600]

        frags = json.loads(_get(f"{base}/debug/fragments"))
        assert frags["fragments"], frags
        for row in frags["fragments"]:
            assert "version" in row and "epoch" in row, row
        assert frags["totals"]["version"] >= 1, frags["totals"]

        print(
            "smoke_rescache OK: "
            f"hits={snap['hits']} misses={snap['misses']} "
            f"invalidations={snap['invalidations']} "
            f"promotions={snap['promotions']} "
            f"maintainedHits={snap['maintainedHits']}"
        )
        return 0
    finally:
        node.stop()


if __name__ == "__main__":
    sys.exit(main())
