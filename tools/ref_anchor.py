"""Reference benchmark anchors: faithful ports of the reference's key
Go benchmarks, timed against this repo's equivalent paths on the same
host, same data.

No Go toolchain exists in this image (BASELINE.md's preferred "run the
reference's Go benchmarks" is impossible), so the named benchmarks are
ported at two levels:

* the ANCHOR side runs a compiled C++ port of the reference's data
  structures and algorithms (native/refanchor.cpp: roaring
  array/bitmap containers, AddN, CountRange, intersectionCount,
  snapshot serialization+fsync) — conservative, i.e. at least as fast
  as the Go original for this work (sorted-merge AddN vs per-position
  btree seeks, no bounds checks, no GC);
* the REPO side runs this framework's real code path for the same
  semantic operation.

Ported benchmarks (reference file:line):
  intersection_count   fragment_internal_test.go:1432
                       BenchmarkFragment_IntersectionCount
  import_standard      fragment_internal_test.go:2166
                       BenchmarkImportStandard (zipf 1.6/50 rows)
  full_snapshot        fragment_internal_test.go:1964
                       BenchmarkFragment_FullSnapshot
  import_update        fragment_internal_test.go:2190
                       BenchmarkImportRoaringUpdate (Rows1000Cols50000)

Prints one JSON object and (with --baseline-md) rewrites the measured
table in BASELINE.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SHARD_WIDTH = 1 << 20  # the reference's default (shardwidth.go)


def zipf_rows(rng: np.random.Generator, num_rows: int, n: int) -> np.ndarray:
    """Row ids with P(k) proportional to 1/(50+k)^1.6 on [0, num_rows)
    — the distribution of the reference's rand.NewZipf(r, 1.6, 50,
    numRows-1) generators (fragment_internal_test.go:2377,2449)."""
    w = 1.0 / np.power(50.0 + np.arange(num_rows, dtype=np.float64), 1.6)
    cdf = np.cumsum(w)
    cdf /= cdf[-1]
    return np.searchsorted(cdf, rng.random(n)).astype(np.uint64)


def _best(f, reps: int) -> float:
    """min-of-reps wall time (noise on a shared host is upward-only)."""
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        f()
        ts.append(time.perf_counter() - t0)
    return min(ts)


def bench_intersection_count(results: dict) -> None:
    """BenchmarkFragment_IntersectionCount: row 1 = every 2nd column of
    10000, row 2 = every 3rd; time |row1 & row2| repeatedly."""
    from pilosa_tpu.ops import _hostops, _refanchor

    cols1 = np.arange(0, 10000, 2, dtype=np.uint64)
    cols2 = np.arange(0, 10000, 3, dtype=np.uint64)

    rb = _refanchor.RefBitmap()
    rb.addn_sorted(1 * SHARD_WIDTH + cols1)
    rb.addn_sorted(2 * SHARD_WIDTH + cols2)
    want = int(np.intersect1d(cols1, cols2).size)
    got = rb.intersection_count(1, 2, SHARD_WIDTH)
    assert got == want, (got, want)
    reps = 2000
    anchor_t = (
        _best(
            lambda: [
                rb.intersection_count(1, 2, SHARD_WIDTH) for _ in range(reps)
            ],
            5,
        )
        / reps
    )
    rb.close()

    # repo: dense host-mirror rows + the host latency tier's fused
    # native kernel — the same unit the executor's cold path runs per
    # fragment (exec/executor.py _host_pair_count_chunk)
    n_words = SHARD_WIDTH // 32
    row1 = np.zeros(n_words, dtype=np.uint32)
    row2 = np.zeros(n_words, dtype=np.uint32)
    np.bitwise_or.at(
        row1, cols1 // 32, np.uint32(1) << (cols1 % 32).astype(np.uint32)
    )
    np.bitwise_or.at(
        row2, cols2 // 32, np.uint32(1) << (cols2 % 32).astype(np.uint32)
    )
    assert _hostops.pair_count(row1, row2, "intersect") == want
    repo_t = (
        _best(
            lambda: [
                _hostops.pair_count(row1, row2, "intersect")
                for _ in range(reps)
            ],
            5,
        )
        / reps
    )
    results["intersection_count"] = {
        "reference": "BenchmarkFragment_IntersectionCount "
        "(fragment_internal_test.go:1432)",
        "anchor_us": round(anchor_t * 1e6, 2),
        "repo_us": round(repo_t * 1e6, 2),
        "repo_vs_anchor": round(anchor_t / repo_t, 3),
        "note": "anchor: array-x-bitmap container loop over ~3.3k "
        "elements; repo: dense 2x128KB fused and+popcount — the dense "
        "layout streams 77x the bytes for a sparse lone pair; the "
        "framework serves repeats from the gram cache and batches on "
        "the MXU instead (see serving_* in bench.py)",
    }


def bench_import_standard(results: dict) -> None:
    """BenchmarkImportStandard: 2^20 (row, col) pairs, rows zipf over
    {2, 1000, 100000} distinct rows, one bulk import into a fresh
    fragment (no snapshot await — the reference enqueues it async)."""
    from pilosa_tpu.core.fragment import Fragment
    from pilosa_tpu.ops import _refanchor

    out = {}
    for num_rows in (2, 1000, 100000):
        rng = np.random.default_rng(1)
        rows = zipf_rows(rng, num_rows, SHARD_WIDTH)
        cols = np.arange(SHARD_WIDTH, dtype=np.uint64)

        def anchor_once():
            rb = _refanchor.RefBitmap()
            pos = np.unique(rows * SHARD_WIDTH + cols)
            rb.addn_sorted(pos)
            # per-affected-row cache update (fragment.go:2085-2096)
            for r in np.unique(rows):
                rb.count_range(
                    int(r) * SHARD_WIDTH, (int(r) + 1) * SHARD_WIDTH
                )
            rb.close()

        def repo_once():
            frag = Fragment(n_words=SHARD_WIDTH // 32)
            frag.import_bits(rows.copy(), cols.copy())

        anchor_t = _best(anchor_once, 3)
        repo_t = _best(repo_once, 3)
        out[f"rows{num_rows}"] = {
            "anchor_mbits_s": round(SHARD_WIDTH / anchor_t / 1e6, 2),
            "repo_mbits_s": round(SHARD_WIDTH / repo_t / 1e6, 2),
            "repo_vs_anchor": round(anchor_t / repo_t, 3),
        }
    out["reference"] = (
        "BenchmarkImportStandard (fragment_internal_test.go:2166)"
    )
    results["import_standard"] = out


def bench_full_snapshot(results: dict) -> None:
    """BenchmarkFragment_FullSnapshot: 100 rows x 2^19 bits (every 2nd
    column), snapshot (serialize + fsync) repeatedly."""
    from pilosa_tpu.core.fragment import Fragment
    from pilosa_tpu.storage.fragmentfile import FragmentFile, SnapshotQueue
    from pilosa_tpu.ops import _refanchor

    cols = np.arange(1, SHARD_WIDTH, 2, dtype=np.uint64)
    rb = _refanchor.RefBitmap()
    for r in range(100):
        rb.addn_sorted(r * SHARD_WIDTH + cols)

    rows_all = np.repeat(np.arange(100, dtype=np.uint64), cols.size)
    cols_all = np.tile(cols, 100)

    with tempfile.TemporaryDirectory() as d:
        anchor_t = _best(
            lambda: rb.snapshot(os.path.join(d, "anchor.snap")), 3
        )
        # store attached BEFORE the setup import, like the reference's
        # mustOpenFragment (attaching after would let open() load the
        # empty file over the populated mirror)
        sq = SnapshotQueue(workers=1)
        frag = Fragment(n_words=SHARD_WIDTH // 32)
        store = FragmentFile(frag, os.path.join(d, "frag"), sq)
        store.open()
        frag.store = store
        frag.import_bits(rows_all, cols_all)
        sq.await_all()

        repo_t = _best(store.snapshot, 3)
        repo_bytes = os.path.getsize(os.path.join(d, "frag"))
        assert repo_bytes > 1_000_000, repo_bytes
        sq.stop()
        store.close()
    rb.close()
    results["full_snapshot"] = {
        "reference": "BenchmarkFragment_FullSnapshot "
        "(fragment_internal_test.go:1964)",
        "anchor_ms": round(anchor_t * 1e3, 1),
        "repo_ms": round(repo_t * 1e3, 1),
        "repo_vs_anchor": round(anchor_t / repo_t, 3),
    }


def bench_import_update(results: dict) -> None:
    """BenchmarkImportRoaringUpdate Rows1000Cols50000: zipf-1000-row
    base (snapshotted), then a 50k-position update import INCLUDING the
    snapshot it triggers (the benchmark calls awaitSnapshot; 50k
    changed bits >> MaxOpN=10000 forces a full rewrite)."""
    from pilosa_tpu.core.fragment import Fragment
    from pilosa_tpu.storage.fragmentfile import FragmentFile, SnapshotQueue
    from pilosa_tpu.ops import _refanchor

    rng = np.random.default_rng(1)
    base_rows = zipf_rows(rng, 1000, SHARD_WIDTH)
    base_cols = np.arange(SHARD_WIDTH, dtype=np.uint64)
    up_rows = zipf_rows(rng, 1000, 50000)
    up_cols = rng.integers(0, SHARD_WIDTH, size=50000).astype(np.uint64)

    with tempfile.TemporaryDirectory() as d:
        def anchor_once():
            rb = _refanchor.RefBitmap()
            rb.addn_sorted(np.unique(base_rows * SHARD_WIDTH + base_cols))
            t0 = time.perf_counter()
            rb.addn_sorted(np.unique(up_rows * SHARD_WIDTH + up_cols))
            for r in np.unique(up_rows):
                rb.count_range(
                    int(r) * SHARD_WIDTH, (int(r) + 1) * SHARD_WIDTH
                )
            rb.snapshot(os.path.join(d, "anchor.snap"))
            dt = time.perf_counter() - t0
            rb.close()
            return dt

        def repo_once():
            sq = SnapshotQueue(workers=1)
            frag = Fragment(n_words=SHARD_WIDTH // 32)
            store = FragmentFile(frag, os.path.join(d, "frag"), sq)
            store.open()
            frag.store = store
            frag.import_bits(base_rows.copy(), base_cols.copy())
            store.snapshot()  # base state snapshotted, like the reference
            t0 = time.perf_counter()
            frag.import_bits(up_rows.copy(), up_cols.copy())
            sq.await_all()
            dt = time.perf_counter() - t0
            sq.stop()
            store.close()
            for fn in os.listdir(d):
                if fn.startswith("frag"):
                    os.unlink(os.path.join(d, fn))
            return dt

        anchor_t = min(anchor_once() for _ in range(3))
        repo_t = min(repo_once() for _ in range(3))
    results["import_update"] = {
        "reference": "BenchmarkImportRoaringUpdate Rows1000Cols50000 "
        "(fragment_internal_test.go:2190)",
        "anchor_ms": round(anchor_t * 1e3, 1),
        "repo_ms": round(repo_t * 1e3, 1),
        "repo_vs_anchor": round(anchor_t / repo_t, 3),
    }


MD_BEGIN = "<!-- ref-anchor:begin -->"
MD_END = "<!-- ref-anchor:end -->"


def update_baseline_md(results: dict, path: str) -> None:
    lines = [
        MD_BEGIN,
        "",
        "## Measured reference anchors (round 5)",
        "",
        "No Go toolchain exists in this image, so the reference's key",
        "benchmarks are PORTED: the anchor side is a compiled C++ port of",
        "the reference's roaring container algorithms (native/refanchor.cpp"
        " —",
        "conservative: sorted-merge AddN is faster than the original's",
        "per-position btree seeks), the repo side is this framework's real",
        "path for the same semantic work, same data, same host "
        "(single-core).",
        "Regenerate: `python tools/ref_anchor.py --baseline-md`.",
        "",
        "| benchmark (reference) | anchor | repo | repo/anchor |",
        "|---|---|---|---|",
    ]
    ic = results["intersection_count"]
    lines.append(
        f"| IntersectionCount (lone sparse pair) | {ic['anchor_us']} us "
        f"| {ic['repo_us']} us | {ic['repo_vs_anchor']}x |"
    )
    for k, v in results["import_standard"].items():
        if k == "reference":
            continue
        lines.append(
            f"| ImportStandard {k} | {v['anchor_mbits_s']} Mbit/s "
            f"| {v['repo_mbits_s']} Mbit/s | {v['repo_vs_anchor']}x |"
        )
    fs = results["full_snapshot"]
    lines.append(
        f"| FullSnapshot | {fs['anchor_ms']} ms | {fs['repo_ms']} ms "
        f"| {fs['repo_vs_anchor']}x |"
    )
    iu = results["import_update"]
    lines.append(
        f"| ImportRoaringUpdate 1000r/50kc | {iu['anchor_ms']} ms "
        f"| {iu['repo_ms']} ms | {iu['repo_vs_anchor']}x |"
    )
    lines += [
        "",
        "repo/anchor > 1 means the repo is faster. The lone sparse",
        "IntersectionCount is the dense layout's worst case by design —",
        "see docs/parity.md; batched and repeat serving regimes are",
        "covered by bench.py's serving_* and batched figures.",
        "",
        MD_END,
    ]
    block = "\n".join(lines)
    with open(path) as f:
        text = f.read()
    if MD_BEGIN in text:
        pre = text[: text.index(MD_BEGIN)]
        post = text[text.index(MD_END) + len(MD_END) :]
        text = pre + block + post
    else:
        text = text.rstrip() + "\n\n" + block + "\n"
    with open(path, "w") as f:
        f.write(text)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--baseline-md", action="store_true")
    args = ap.parse_args()

    # the anchors never touch the device; keep jax off the accelerator
    # so import side-effects can't skew the host timings
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # graftlint: disable=exception-hygiene -- best-effort platform pin in a benchmark CLI; older jax without the flag still measures correctly
        pass

    from pilosa_tpu.ops import _refanchor

    if _refanchor.load() is None:
        print(json.dumps({"error": "refanchor library unavailable"}))
        return 1

    results: dict = {}
    bench_intersection_count(results)
    bench_import_standard(results)
    bench_full_snapshot(results)
    bench_import_update(results)
    print(json.dumps(results, indent=1))
    if args.baseline_md:
        update_baseline_md(
            results,
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "BASELINE.md"),
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
