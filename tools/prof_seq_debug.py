"""Debug the 27ms-vs-5ms cold execute gap seen in bench.py: replicate
the bench's exact pre-state (device gram section first), then time the
cold loop unsorted and cProfile it."""
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pilosa_tpu.ops import kernels

print("platform:", jax.devices()[0].platform, flush=True)

S, R, W = 160, 64, 32768
key = jax.random.PRNGKey(7)
k1, k2 = jax.random.split(key)
bits = jax.random.bits(k1, (S, R, W), dtype=jnp.uint32) & jax.random.bits(
    k2, (S, R, W), dtype=jnp.uint32
)
np.asarray(bits[0, 0, :4])

rng = np.random.default_rng(3)
B = 1024
ras = rng.integers(0, R, size=B).astype(np.int64)
rbs = rng.integers(0, R, size=B).astype(np.int64)

# exact bench pre-state: salted gram launches + stacked pull
gram_salted = jax.jit(lambda b, s: kernels.gram_matrix_traced(b ^ s))
salts = [jnp.uint32(i) for i in range(9)]
reps = 4
np.asarray(jnp.stack([gram_salted(bits, salts[-1]) for _ in range(reps)]))
grams = [gram_salted(bits, salts[r]) for r in range(reps)]
grams_np = np.asarray(jnp.stack(grams)).astype(np.int64)
counts = [kernels.pair_counts_from_gram(g, ras, rbs, "intersect") for g in grams_np]
print("gram section done", flush=True)

from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.exec.executor import Executor

h = Holder(n_words=W)
idx = h.create_index("seq")
f = idx.create_field("f")
v = f.create_view_if_not_exists(VIEW_STANDARD)
seq_rng = np.random.default_rng(13)
sub_shards = max(1, S // 16)
for s in range(S):
    words = seq_rng.integers(0, 2**32, size=(R, W), dtype=np.uint32) & \
        seq_rng.integers(0, 2**32, size=(R, W), dtype=np.uint32)
    frag = v.create_fragment_if_not_exists(s)
    for r in range(R):
        frag.set_row_words(r, words[r])
print("setup done", flush=True)

ex = Executor(h)
ex._PAIR_SINGLE_WARM = 10**9
q0 = f"Count(Intersect(Row(f={int(ras[0])}), Row(f={int(rbs[0])})))"
ex.execute("seq", q0)

n_seq = 30
lat = []
for i in range(n_seq):
    t1 = time.perf_counter()
    ex.execute(
        "seq",
        f"Count(Intersect(Row(f={int(ras[i % B])}), Row(f={int(rbs[i % B])})))",
    )
    lat.append(time.perf_counter() - t1)
print("unsorted ms:", [round(p * 1e3, 1) for p in lat], flush=True)

import cProfile
import pstats

pr = cProfile.Profile()
pr.enable()
for i in range(n_seq):
    ex.execute(
        "seq",
        f"Count(Intersect(Row(f={int(ras[i % B])}), Row(f={int(rbs[i % B])})))",
    )
pr.disable()
pstats.Stats(pr).sort_stats("tottime").print_stats(18)
