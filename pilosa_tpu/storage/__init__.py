"""Host-side storage: the roaring interchange codec, per-fragment
snapshot+op-log files, and the on-disk holder directory tree (reference:
roaring serialization roaring/roaring.go:1044-1126 + op log :4415-4610,
fragment persistence fragment.go:311-456, holder tree holder.go:134-198).

Storage never touches the device data path: fragments snapshot from their
host mirrors, and loads populate host mirrors which lazily sync to HBM."""
