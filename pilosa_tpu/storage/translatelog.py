"""Append-only key-translation log (reference: translate.go TranslateFile
— an mmap'd append-only log of InsertColumn/InsertRow entries
(translate.go:37-40) with in-memory hash indexes rebuilt on load).

Binary format, little-endian:

    header: magic u32 = 0x504b4c31 ("PKL1")
    record: u8 type (1 = insert)
            u16 index_len, u16 field_len, u32 key_len
            u64 id
            index utf-8, field utf-8, key utf-8

A torn tail record (crash mid-append) truncates the replay at the last
complete record, like the roaring op log.
"""

from __future__ import annotations

import os
import struct
import threading

from pilosa_tpu.core import translate
from pilosa_tpu.core.translate import TranslateStore

MAGIC = 0x504B4C31
_HDR = struct.Struct("<I")
_REC = struct.Struct("<BHHIQ")
REC_INSERT = 1


class TranslateLog:
    """Wires a TranslateStore to an on-disk append-only log."""

    def __init__(self, store: TranslateStore, path: str):
        self.store = store
        self.path = path
        self._lock = threading.Lock()
        self._f = None

    def open(self) -> None:
        exists = os.path.exists(self.path)
        if exists:
            self._replay()
        self._f = open(self.path, "ab")
        if not exists or self._f.tell() == 0:
            self._f.write(_HDR.pack(MAGIC))
            self._f.flush()
        # hook AFTER replay so replayed inserts don't re-append
        self.store.on_insert = self._append

    def _replay(self) -> None:
        with open(self.path, "rb") as f:
            data = f.read()
        if len(data) < _HDR.size or _HDR.unpack_from(data, 0)[0] != MAGIC:
            return
        pos = _HDR.size
        good = pos
        # Batch CONTIGUOUS same-(index, field) runs for set_mapping
        # efficiency while preserving the global record order — the
        # rebuilt in-memory entry log must match the original append
        # order so replica stream offsets stay meaningful across a
        # primary restart.
        run_space: tuple[str, str] | None = None
        run_keys: list[str] = []
        run_ids: list[int] = []

        def flush_run():
            if run_space is not None and run_keys:
                self.store.set_mapping(
                    run_space[0], run_space[1], run_keys, run_ids
                )

        while pos + _REC.size <= len(data):
            typ, ilen, flen, klen, id_ = _REC.unpack_from(data, pos)
            end = pos + _REC.size + ilen + flen + klen
            if typ != REC_INSERT or end > len(data):
                break
            p = pos + _REC.size
            index = data[p : p + ilen].decode()
            field = data[p + ilen : p + ilen + flen].decode()
            key = data[p + ilen + flen : end].decode()
            if (index, field) != run_space:
                flush_run()
                run_space = (index, field)
                run_keys, run_ids = [], []
            run_keys.append(key)
            run_ids.append(id_)
            pos = good = end
        flush_run()
        if good < len(data):
            # torn tail: truncate so future appends start at a record edge
            with open(self.path, "r+b") as f:
                f.truncate(good)

    def _append(self, index: str, field: str, key: str, id_: int) -> None:
        ib, fb, kb = index.encode(), field.encode(), key.encode()
        rec = _REC.pack(REC_INSERT, len(ib), len(fb), len(kb), id_) + ib + fb + kb
        # counted before the file lock: telemetry never queues behind I/O
        translate.translate_stats.count("translate_log_appends", 1)
        with self._lock:
            if self._f is None:
                return
            self._f.write(rec)
            self._f.flush()

    def sync(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()
                os.fsync(self._f.fileno())

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()
                self._f.close()
                self._f = None
        if self.store.on_insert == self._append:
            self.store.on_insert = None
