"""Per-fragment persistence: roaring snapshot + append-only op log.

The reference persists each fragment as one roaring file whose container
section is a snapshot and whose tail is an op log; mutations append ops and
the whole file is atomically rewritten once ``opN > MaxOpN`` (reference
fragment.go:84 MaxOpN=10000, :311-456 openStorage, :2325-2381 snapshot via
temp file + rename, docs/architecture.md). Same model here, writing from
the fragment's host mirror.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time

import numpy as np

from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.obs import events as ev
from pilosa_tpu.ops import bitops
from pilosa_tpu.storage import roaring
from pilosa_tpu.testing import faults

logger = logging.getLogger(__name__)

# reference fragment.go:84.
MAX_OP_N = 10000

# WAL fsync policy — see _append_many.  "snapshot" (default, reference
# durability parity) | "batch" (fsync every WAL batch).
WAL_FSYNC = os.environ.get("PILOSA_TPU_WAL_FSYNC", "snapshot")

# Batch ops chunk size: bounds the pure-python fnv checksum cost per record.
_BATCH_CHUNK = 65536


class FragmentFile:
    """Owns the on-disk file of one fragment."""

    def __init__(
        self,
        fragment: Fragment,
        path: str,
        snapshot_queue: "SnapshotQueue | None" = None,
        journal=None,
    ):
        self.fragment = fragment
        self.path = path
        self.snapshot_queue = snapshot_queue
        self.journal = journal  # EventJournal; snapshot compactions record
        self.last_snapshot_at: float | None = None
        self._lock = threading.Lock()
        self._fh = None
        self._closed = False
        self.op_n = 0
        # monotonic append counter — unlike op_n it NEVER resets, so the
        # optimistic snapshot's "no op landed since my copy" check can't
        # be fooled by op_n cycling back to the same value (ABA) after a
        # concurrent snapshot reset it
        self.mut_seq = 0
        # per-mutation op batching (begin_batch/end_batch): buffered
        # positions flushed as single batch records. Caller guarantees the
        # add and remove sets of one batch are disjoint (true for all
        # Fragment mutators).
        self._batch_depth = 0
        self._batch_add: list[np.ndarray] = []
        self._batch_remove: list[np.ndarray] = []
        # Migration delta taps (cluster/migration.py): while a shard
        # streams to a new owner, a tap pinned here mirrors every
        # appended record so the target can replay writes that landed
        # after its snapshot cut.  Fed under the store lock — tap order
        # matches file order exactly.
        self._taps: list = []
        fragment.store = self

    # -- load ---------------------------------------------------------------

    def open(self) -> None:
        """Load snapshot + replay op log into the fragment's host mirror."""
        if not os.path.exists(self.path) or os.path.getsize(self.path) == 0:
            # seed with an empty-bitmap header so the file always starts
            # with a valid snapshot section (the reference writes the
            # bitmap before appending ops, fragment.go:311-456)
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(self.path, "wb") as f:
                f.write(roaring.serialize(np.empty(0, dtype=np.uint64)))
        if os.path.exists(self.path):
            with open(self.path, "rb") as f:
                data = f.read()
            if data:
                positions, self.op_n = roaring.deserialize_with_opcount(data)
                width = self.fragment.shard_width
                rows_arr = positions // np.uint64(width)
                cols_arr = (positions % np.uint64(width)).astype(np.int64)
                row_ids, inverse = np.unique(rows_arr, return_inverse=True)
                host_rows = {}
                for i, rid in enumerate(row_ids):
                    mask = inverse == i
                    host_rows[int(rid)] = bitops.pack_columns(
                        cols_arr[mask], self.fragment.n_words
                    )
                self.fragment.load_host_rows(host_rows)
        self._fh = open(self.path, "ab")

    # -- op append ----------------------------------------------------------

    def _positions(self, row: int, mask: np.ndarray) -> np.ndarray:
        self.check_row(row)
        width = self.fragment.shard_width
        return np.uint64(row) * np.uint64(width) + bitops.unpack_columns(mask)

    def _positions_multi(
        self, rows: np.ndarray, masks: np.ndarray
    ) -> np.ndarray:
        """Positions for many (row, mask) pairs — the sustained-ingest hot
        path. Masks are sparse relative to the full row width, so only the
        nonzero *words* are expanded to bit offsets (a 32-wide table per
        set word) rather than unpacking every bit of every row."""
        width = self.fragment.shard_width
        for r in rows:
            self.check_row(int(r))
        rows = rows.astype(np.uint64)
        masks = np.ascontiguousarray(masks, dtype=np.uint32)
        # native ctz walk per row when available (one ctypes call per
        # row, each a contiguous mask) — the numpy blockwise expansion
        # below is the no-toolchain fallback
        from pilosa_tpu.ops import _hostops

        if _hostops.load() is not None:
            parts = [
                _hostops.extract_positions(
                    masks[i], int(rows[i]) * width
                )
                for i in range(len(rows))
            ]
            if not parts:
                return np.empty(0, dtype=np.uint64)
            return np.concatenate(parts)
        sl, wi = np.nonzero(masks)
        if not len(sl):
            return np.empty(0, dtype=np.uint64)
        words = np.ascontiguousarray(masks[sl, wi])
        word_pos = rows[sl] * np.uint64(width) + wi.astype(np.uint64) * np.uint64(32)
        # Expand each nonzero word's bits blockwise via unpackbits (uint8
        # end to end, no wider intermediate): 32 bytes per word per block
        # keeps the transient bounded (~64 MiB) even for dense fragments,
        # where one unblocked expansion would be multi-GiB.
        block = (64 << 20) // 32
        parts = []
        for b0 in range(0, len(words), block):
            w = words[b0 : b0 + block]
            bits = np.unpackbits(
                w.view(np.uint8).reshape(len(w), 4),
                axis=1,
                bitorder="little",
            )
            wsel, b = np.nonzero(bits)
            # row-major nonzero keeps the (row, word, bit) sort order the
            # previous full-unpack implementation produced
            parts.append(word_pos[b0 + wsel] + b.astype(np.uint64))
        return np.concatenate(parts)

    def _append(self, record: bytes, count: int) -> None:
        self._append_many([record], count)

    def _append_many(self, records: list[bytes], count: int) -> None:
        """Append several records with ONE flush (each record carries
        its own checksum, so a torn tail replays cleanly).

        fsync policy (``PILOSA_TPU_WAL_FSYNC``): the default
        ``snapshot`` syncs only snapshot files — exactly the
        reference's durability (its op-log writes land in the OS page
        cache with no Sync, roaring.go:1655 writeOp; only snapshot
        rewrites fsync, fragment.go:2750), so a process crash loses
        nothing and an OS/power crash can lose ops since the last
        snapshot.  ``batch`` additionally fsyncs every WAL batch —
        stronger than the reference, at ~35 ms per sync on this host's
        disk (it was the bottleneck of sustained ingest)."""
        if not records:
            return
        # Fault-injection hook (testing/faults.py): raises OSError so a
        # chaos test can see a failed op-log append surface through the
        # import path the way a real ENOSPC would.
        faults.disk_write_fault(self.path)
        with self._lock:
            if self._fh is None:
                self._fh = open(self.path, "ab")
            for record in records:
                self._fh.write(record)
            self._fh.flush()
            if WAL_FSYNC == "batch":
                os.fsync(self._fh.fileno())
            self.op_n += count
            self.mut_seq += 1
            for tap in self._taps:
                tap.feed(records, count)
        if self.op_n > MAX_OP_N:
            self.request_snapshot()

    # -- migration taps -----------------------------------------------------

    def add_tap(self, tap) -> None:
        with self._lock:
            self._taps.append(tap)

    def remove_tap(self, tap) -> None:
        with self._lock:
            try:
                self._taps.remove(tap)
            except ValueError:
                pass

    def check_row(self, row: int) -> None:
        """Raise BEFORE any mutation if a row id cannot be persisted
        (positions are row*width+col in uint64, so rows are bounded at
        ~2^44 for the default width once a store is attached)."""
        width = self.fragment.shard_width
        if row > (2**64 - 1) // width:
            raise ValueError(
                f"row id {row} too large to persist at shard width {width}"
            )

    def _pos(self, row: int, col: int) -> int:
        self.check_row(row)
        return row * self.fragment.shard_width + col

    # -- batching ----------------------------------------------------------

    def begin_batch(self) -> None:
        self._batch_depth += 1

    def end_batch(self) -> None:
        self._batch_depth -= 1
        if self._batch_depth > 0:
            return
        adds, self._batch_add = self._batch_add, []
        removes, self._batch_remove = self._batch_remove, []
        # Group-commit: the whole batch — add AND remove records — lands
        # in ONE locked append/flush (and one fsync under the "batch"
        # WAL policy), so a pipeline-merged apply costs a single op-log
        # write no matter how many imports coalesced into it.
        records: list[bytes] = []
        count = 0
        if adds:
            positions = np.concatenate(adds)
            records += self._batch_records(roaring.OP_ADD_BATCH, positions)
            count += len(positions)
        if removes:
            positions = np.concatenate(removes)
            records += self._batch_records(roaring.OP_REMOVE_BATCH, positions)
            count += len(positions)
        if records:
            self._append_many(records, count)

    def _batch_records(self, op_type: int, positions: np.ndarray) -> list[bytes]:
        return [
            roaring.encode_op(op_type, positions[i : i + _BATCH_CHUNK])
            for i in range(0, len(positions), _BATCH_CHUNK)
        ]

    def _emit_batch(self, op_type: int, positions: np.ndarray) -> None:
        self._append_many(
            self._batch_records(op_type, positions), len(positions)
        )

    def log_add(self, row: int, col: int) -> None:
        pos = self._pos(row, col)
        if self._batch_depth:
            self._batch_add.append(np.array([pos], dtype=np.uint64))
            return
        self._append(roaring.encode_op(roaring.OP_ADD, pos), 1)

    def log_remove(self, row: int, col: int) -> None:
        pos = self._pos(row, col)
        if self._batch_depth:
            self._batch_remove.append(np.array([pos], dtype=np.uint64))
            return
        self._append(roaring.encode_op(roaring.OP_REMOVE, pos), 1)

    def log_add_mask(self, row: int, mask: np.ndarray) -> None:
        positions = self._positions(row, mask)
        if self._batch_depth:
            self._batch_add.append(positions)
            return
        self._emit_batch(roaring.OP_ADD_BATCH, positions)

    def log_remove_mask(self, row: int, mask: np.ndarray) -> None:
        positions = self._positions(row, mask)
        if self._batch_depth:
            self._batch_remove.append(positions)
            return
        self._emit_batch(roaring.OP_REMOVE_BATCH, positions)

    def log_add_masks(self, rows: np.ndarray, masks: np.ndarray) -> None:
        positions = self._positions_multi(rows, masks)
        if self._batch_depth:
            self._batch_add.append(positions)
            return
        self._emit_batch(roaring.OP_ADD_BATCH, positions)

    def log_remove_masks(self, rows: np.ndarray, masks: np.ndarray) -> None:
        positions = self._positions_multi(rows, masks)
        if self._batch_depth:
            self._batch_remove.append(positions)
            return
        self._emit_batch(roaring.OP_REMOVE_BATCH, positions)

    def log_add_positions(self, positions: np.ndarray) -> None:
        """Bulk-add op records from PRE-COMPUTED absolute positions —
        the sustained-ingest hot path (Fragment.import_bits derives the
        changed positions as a by-product of its merge, so no mask
        unpack happens here; reference roaring.go:1463's rowSet change
        tracking plays the same role).  Caller has check_row'd the rows."""
        positions = np.ascontiguousarray(positions, dtype=np.uint64)
        if self._batch_depth:
            self._batch_add.append(positions)
            return
        self._emit_batch(roaring.OP_ADD_BATCH, positions)

    def log_remove_positions(self, positions: np.ndarray) -> None:
        positions = np.ascontiguousarray(positions, dtype=np.uint64)
        if self._batch_depth:
            self._batch_remove.append(positions)
            return
        self._emit_batch(roaring.OP_REMOVE_BATCH, positions)

    # -- snapshot -----------------------------------------------------------

    def request_snapshot(self) -> None:
        if self.snapshot_queue is not None:
            self.snapshot_queue.enqueue(self)
        else:
            self.snapshot()

    # optimistic snapshot attempts before falling back to holding the
    # fragment lock for the whole rewrite (continuous writers would
    # otherwise livelock the retry loop)
    _SNAPSHOT_RETRIES = 3

    def snapshot(self) -> None:
        """Atomic rewrite: temp file + rename (reference
        fragment.go:2335-2381).

        The expensive work (position extraction + roaring encode + fsync)
        runs WITHOUT the fragment lock, from a copied state — a snapshot
        worker must not stall concurrent queries/ingest for the whole
        rewrite. The swap then happens under the lock only if no op was
        appended since the copy (an op landing in between would be in the
        fragment's mirror but lost from the replaced file's op log);
        otherwise retry with a fresh copy, degrading to the fully locked
        path after _SNAPSHOT_RETRIES so a continuous writer can't
        livelock us. Lock order fragment->store matches the writer path."""
        for attempt in range(self._SNAPSHOT_RETRIES + 1):
            locked_rewrite = attempt == self._SNAPSHOT_RETRIES
            with self.fragment._lock:
                if locked_rewrite:
                    # final attempt: hold the lock across extract + swap
                    with self._lock:
                        if self._closed:
                            return
                        self._write_snapshot_file(
                            self._encode_rows(*self.fragment.snapshot_rows())
                        )
                        return
                with self._lock:
                    if self._closed:
                        # A snapshot queued before the store was detached
                        # (e.g. the fragment was dropped by resize
                        # cleanup) must not resurrect the deleted file.
                        return
                    seq_at = self.mut_seq
                rids, rwords = self.fragment.snapshot_rows()
            data = self._encode_rows(rids, rwords)
            with self.fragment._lock, self._lock:
                if self._closed:
                    return
                if self.mut_seq != seq_at:
                    continue  # an op landed mid-encode; redo from fresh state
                self._write_snapshot_file(data)
                return

    def _write_snapshot_file(self, data: bytes) -> None:
        """Swap in an encoded snapshot (both locks held)."""
        faults.disk_write_fault(self.path)
        tmp = self.path + ".snapshotting"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        if self._fh is not None:
            self._fh.close()
        os.replace(tmp, self.path)
        self._fh = open(self.path, "ab")
        ops_compacted = self.op_n
        self.op_n = 0
        self.last_snapshot_at = time.time()
        if self.journal is not None:
            frag = self.fragment
            self.journal.record(
                ev.EVENT_SNAPSHOT,
                path=self.path,
                bytes=len(data),
                ops_compacted=ops_compacted,
                shard=getattr(frag, "shard", None),
            )

    def _encode_rows(self, rids: np.ndarray, rwords: np.ndarray) -> bytes:
        """Snapshot bytes for ascending row ids + stacked words: the
        native words->roaring streaming encoder when available, else
        the positions pipeline (byte-identical output)."""
        data = roaring.serialize_rows(rids, rwords)
        if data is not None:
            return data
        return roaring.serialize(self._positions_multi(rids, rwords))

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._fh is not None:
                # Under WAL_FSYNC='snapshot' appended ops are only
                # flushed to the page cache; a crash right after a clean
                # close would lose the op-log tail.  Sync on the way out.
                try:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                except (OSError, ValueError):
                    pass  # best-effort: close() must not raise on shutdown
                self._fh.close()
                self._fh = None


class SnapshotQueue:
    """Background snapshot pool (reference fragment.go:185-239: depth 100,
    2 workers, await support)."""

    def __init__(self, workers: int = 2, depth: int = 100):
        self._queue: queue.Queue = queue.Queue(maxsize=depth)
        self._pending: set[int] = set()
        self._lock = threading.Lock()
        self._workers = [
            threading.Thread(target=self._run, daemon=True) for _ in range(workers)
        ]
        for w in self._workers:
            w.start()

    def enqueue(self, store: FragmentFile) -> None:
        with self._lock:
            if id(store) in self._pending:
                return
            self._pending.add(id(store))
        try:
            self._queue.put_nowait(store)
        except queue.Full:
            # queue full: snapshot synchronously (reference enqueues
            # blockingly; sync fallback keeps the writer moving)
            with self._lock:
                self._pending.discard(id(store))
            store.snapshot()

    def _run(self) -> None:
        while True:
            store = self._queue.get()
            if store is None:
                return
            try:
                store.snapshot()
            except Exception:
                # e.g. the fragment's directory was deleted mid-flight;
                # never let a failed snapshot kill the worker
                logger.exception("snapshot failed for %s", store.path)
            finally:
                with self._lock:
                    self._pending.discard(id(store))
                self._queue.task_done()

    def await_all(self) -> None:
        self._queue.join()

    def stop(self) -> None:
        for _ in self._workers:
            self._queue.put(None)
