"""ctypes bindings for the native C++ roaring codec (native/roaring_codec.cpp).

The reference's storage hot loops are compiled Go; here they are C++
behind a C ABI.  The shared library is built on demand through the
shared loader (pilosa_tpu/nativelib.py), and every entry point degrades
to ``None`` so callers fall back to the vectorized-numpy codec when no
toolchain exists.  Set ``PILOSA_TPU_NO_NATIVE=1`` to force the Python
path.
"""

from __future__ import annotations

import ctypes
import os
import threading

import numpy as np

from pilosa_tpu import nativelib

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "roaring_codec.cpp",
)
_LIB_PATH = os.path.join(os.path.dirname(_SRC), "libpilosa_native.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False
_has_fnv = False  # set at load(): the symbol is absent from older .so builds
_has_deser_into = False  # likewise (added with the ingest pipeline)


def load() -> ctypes.CDLL | None:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        _lib = nativelib.load(_SRC, _LIB_PATH, _bind)
        return _lib


def _bind(lib: ctypes.CDLL) -> None:
        lib.rt_serialize.restype = ctypes.c_int
        lib.rt_serialize.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_size_t,
            ctypes.c_uint8,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.rt_serialize_words.restype = ctypes.c_int
        lib.rt_serialize_words.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64,
            ctypes.c_uint8,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.rt_deserialize.restype = ctypes.c_int
        lib.rt_deserialize.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.rt_popcount.restype = ctypes.c_uint64
        global _has_deser_into
        try:
            lib.rt_deserialize_into.restype = ctypes.c_int
            lib.rt_deserialize_into.argtypes = [
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_size_t),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            _has_deser_into = True
        except AttributeError:
            _has_deser_into = False
        global _has_fnv
        try:
            lib.rt_fnv32a.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32,
            ]
            lib.rt_fnv32a.restype = ctypes.c_uint32
            _has_fnv = True
        except AttributeError:
            # an older prebuilt library without the symbol: fnv32a()
            # degrades to None like every other entry point
            _has_fnv = False
        lib.rt_popcount.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t,
        ]
        lib.rt_free.restype = None
        lib.rt_free.argtypes = [ctypes.c_void_p]


def serialize(positions: np.ndarray, flags: int = 0) -> bytes | None:
    lib = load()
    if lib is None:
        return None
    positions = np.ascontiguousarray(positions, dtype=np.uint64)
    out = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_size_t()
    rc = lib.rt_serialize(
        positions.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        positions.size,
        flags,
        ctypes.byref(out),
        ctypes.byref(out_len),
    )
    if rc != 0:
        return None
    try:
        return ctypes.string_at(out, out_len.value)
    finally:
        lib.rt_free(out)


def serialize_words(
    row_ids: np.ndarray,
    slots: np.ndarray,
    words: np.ndarray,
    flags: int = 0,
) -> bytes | None:
    """Roaring-serialize straight from dense row words (uint32
    [capacity, n_words] mirror; ``slots[r]`` is the word row of
    ascending ``row_ids[r]``) without materializing a positions array —
    byte-identical to ``serialize(positions)``.  None when
    unavailable."""
    lib = load()
    if lib is None:
        return None
    row_ids = np.ascontiguousarray(row_ids, dtype=np.uint64)
    slots = np.ascontiguousarray(slots, dtype=np.int64)
    if not words.flags["C_CONTIGUOUS"] or words.dtype != np.uint32:
        words = np.ascontiguousarray(words, dtype=np.uint32)
    out = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_size_t()
    rc = lib.rt_serialize_words(
        row_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        slots.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        row_ids.size,
        words.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        words.shape[-1],
        flags,
        ctypes.byref(out),
        ctypes.byref(out_len),
    )
    if rc != 0:
        return None
    try:
        return ctypes.string_at(out, out_len.value)
    finally:
        lib.rt_free(out)


def deserialize(data: bytes) -> tuple[np.ndarray, int] | None:
    """(sorted positions, op count) or None on parse failure/unavailable."""
    lib = load()
    if lib is None:
        return None
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
    out = ctypes.POINTER(ctypes.c_uint64)()
    out_n = ctypes.c_size_t()
    ops = ctypes.c_uint64()
    rc = lib.rt_deserialize(
        buf, len(data), ctypes.byref(out), ctypes.byref(out_n), ctypes.byref(ops)
    )
    if rc != 0:
        return None
    try:
        positions = np.ctypeslib.as_array(out, shape=(out_n.value,)).copy()
    finally:
        lib.rt_free(out)
    return positions.astype(np.uint64), int(ops.value)


def deserialize_into(
    data: bytes, out: np.ndarray
) -> tuple[int, int] | None:
    """Decode ``data`` directly into the caller's uint64 buffer ``out``
    (the staging-buffer zero-copy path: the input bytes are read in
    place and the positions land in ``out`` with no intermediate
    malloc/copy).  Returns (count, op_count); raises ValueError when
    ``out`` is too small, with the required capacity in the message;
    None on parse failure or when the library (or this symbol, in an
    older prebuilt .so) is unavailable."""
    lib = load()
    if lib is None or not _has_deser_into:
        return None
    src = np.frombuffer(data, dtype=np.uint8)  # zero-copy view
    if not (out.dtype == np.uint64 and out.flags["C_CONTIGUOUS"]):
        raise ValueError("staging buffer must be C-contiguous uint64")
    out_n = ctypes.c_size_t()
    ops = ctypes.c_uint64()
    rc = lib.rt_deserialize_into(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        src.size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        out.size,
        ctypes.byref(out_n),
        ctypes.byref(ops),
    )
    if rc == 3:
        raise ValueError(f"staging buffer too small: need {out_n.value}")
    if rc != 0:
        return None
    return int(out_n.value), int(ops.value)


def popcount(data: bytes | np.ndarray) -> int | None:
    lib = load()
    if lib is None:
        return None
    arr = np.ascontiguousarray(
        np.frombuffer(data, dtype=np.uint8) if isinstance(data, bytes) else data.view(np.uint8)
    )
    return int(
        lib.rt_popcount(
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), arr.size
        )
    )


def fnv32a(h: int, chunk: bytes) -> int | None:
    """One FNV-1a round over ``chunk`` continuing from ``h``; None when
    the native library (or this symbol, in an older prebuilt .so) is
    unavailable."""
    lib = load()
    if lib is None or not _has_fnv:
        return None
    return int(lib.rt_fnv32a(chunk, len(chunk), h))
