"""Roaring bitmap file codec — Pilosa's 64-bit variant plus the official
32-bit spec, implemented fresh in vectorized numpy.

Format (documented in reference docs/architecture.md and implemented at
reference roaring/roaring.go:1044-1126 writer, :1562-1654 pilosa reader,
:5076+ official reader, ops :4415-4610):

Pilosa variant, all little-endian:
  bytes 0-1   magic 12348; byte 2 storage version (0); byte 3 user flags
  bytes 4-7   container count N
  descriptive header, 12 bytes/container: u64 key, u16 type, u16 (card-1)
  offset header, 4 bytes/container: u32 absolute file offset of data
  container data:
      array:  u16 values, sorted
      bitmap: 1024 x u64 words
      run:    u16 run count, then [u16 start, u16 last] inclusive pairs
  op log (optional, to EOF): records
      u8 type; u64 value/len; u32 fnv1a checksum; payload
      types: 0 add, 1 remove, 2 addBatch, 3 removeBatch,
             4 addRoaring, 5 removeRoaring (payload: u32 opN + bytes)

Official spec (read-only interchange): cookie 12346 (+u32 container count)
or 12347 (count in cookie high bits, run bitset present), u16 keys.
"""

from __future__ import annotations

import struct

import numpy as np

from pilosa_tpu.storage import _native

MAGIC = 12348
COOKIE_NO_RUN = 12346  # official spec
COOKIE_RUN = 12347  # official spec w/ run containers

CONTAINER_ARRAY = 1
CONTAINER_BITMAP = 2
CONTAINER_RUN = 3

ARRAY_MAX_SIZE = 4096  # reference roaring.go:1984
RUN_MAX_SIZE = 2048  # reference roaring.go:1987

OP_ADD = 0
OP_REMOVE = 1
OP_ADD_BATCH = 2
OP_REMOVE_BATCH = 3
OP_ADD_ROARING = 4
OP_REMOVE_ROARING = 5

_FNV_OFFSET = 0x811C9DC5
_FNV_PRIME = 0x01000193


def _fnv32a(*chunks: bytes) -> int:
    h = _FNV_OFFSET
    for chunk in chunks:
        nh = _native.fnv32a(h, bytes(chunk))
        if nh is not None:
            h = nh
            continue
        for b in chunk:
            h ^= b
            h = (h * _FNV_PRIME) & 0xFFFFFFFF
    return h


class RoaringError(Exception):
    pass


# ---------------------------------------------------------------------------
# Serialization
# ---------------------------------------------------------------------------


def serialize_rows(
    row_ids: np.ndarray, words: np.ndarray, flags: int = 0
) -> bytes | None:
    """Ascending row ids + stacked words [n, n_words] -> roaring file
    bytes, streamed straight off the dense words by the native codec
    (byte-identical to ``serialize`` on the extracted positions, with
    no 8-bytes-per-bit positions array); None when the native codec is
    unavailable — callers fall back to the positions path."""
    if not len(row_ids):
        return _native.serialize(np.empty(0, dtype=np.uint64), flags)
    slots = np.arange(len(row_ids), dtype=np.int64)
    return _native.serialize_words(
        np.asarray(row_ids, dtype=np.uint64), slots, words, flags
    )


def serialize(positions: np.ndarray, flags: int = 0) -> bytes:
    """Sorted uint64 bit positions -> Pilosa roaring file bytes.

    Prefers the native C++ codec (native/roaring_codec.cpp, byte-identical
    output); ``_serialize_py`` is the no-toolchain numpy fallback."""
    native = _native.serialize(positions, flags)
    if native is not None:
        return native
    return _serialize_py(positions, flags)


def _serialize_py(positions: np.ndarray, flags: int = 0) -> bytes:
    positions = np.asarray(positions, dtype=np.uint64)
    if positions.size and np.any(positions[1:] <= positions[:-1]):
        positions = np.unique(positions)
    keys = positions >> np.uint64(16)
    lows = (positions & np.uint64(0xFFFF)).astype(np.uint16)
    ukeys, starts = np.unique(keys, return_index=True)
    bounds = np.append(starts, len(positions))

    headers = []
    datas = []
    for i, key in enumerate(ukeys):
        vals = lows[bounds[i] : bounds[i + 1]]
        n = len(vals)
        # runs: count of consecutive-value breaks
        if n:
            breaks = np.flatnonzero(np.diff(vals.astype(np.int64)) != 1)
            run_count = len(breaks) + 1
        else:
            run_count = 0
        array_size = 2 * n
        run_size = 2 + 4 * run_count
        bitmap_size = 8192
        best = min(
            (array_size if n <= ARRAY_MAX_SIZE else 1 << 30, CONTAINER_ARRAY),
            (run_size if run_count <= RUN_MAX_SIZE else 1 << 30, CONTAINER_RUN),
            (bitmap_size, CONTAINER_BITMAP),
            key=lambda t: t[0],
        )
        ctype = best[1]
        if ctype == CONTAINER_ARRAY:
            data = vals.astype("<u2").tobytes()
        elif ctype == CONTAINER_RUN:
            edges = np.concatenate(([0], breaks + 1, [n]))
            runs = np.empty((run_count, 2), dtype="<u2")
            runs[:, 0] = vals[edges[:-1]]
            runs[:, 1] = vals[edges[1:] - 1]
            data = struct.pack("<H", run_count) + runs.tobytes()
        else:
            words = np.zeros(8192, dtype=np.uint8)
            np.bitwise_or.at(
                words, (vals >> np.uint16(3)).astype(np.int64), np.uint8(1) << (vals & np.uint16(7)).astype(np.uint8)
            )
            data = words.tobytes()
        headers.append((int(key), ctype, n))
        datas.append(data)

    count = len(ukeys)
    out = bytearray()
    cookie = MAGIC | (flags << 24)
    out += struct.pack("<II", cookie, count)
    for key, ctype, n in headers:
        out += struct.pack("<QHH", key, ctype, n - 1)
    offset = 8 + count * 12 + count * 4
    for data in datas:
        out += struct.pack("<I", offset)
        offset += len(data)
    for data in datas:
        out += data
    return bytes(out)


def container_stats(positions: np.ndarray) -> dict:
    """Per-container-type counts for sorted uint64 positions, using the
    same array/run/bitmap selection rules as :func:`serialize` — the
    introspection view (/debug/fragments) reports what the codec would
    actually write, without encoding anything."""
    positions = np.asarray(positions, dtype=np.uint64)
    if positions.size and np.any(positions[1:] <= positions[:-1]):
        positions = np.unique(positions)
    counts = {"array": 0, "run": 0, "bitmap": 0}
    keys = positions >> np.uint64(16)
    lows = (positions & np.uint64(0xFFFF)).astype(np.uint16)
    ukeys, starts = np.unique(keys, return_index=True)
    bounds = np.append(starts, len(positions))
    for i in range(len(ukeys)):
        vals = lows[bounds[i] : bounds[i + 1]]
        n = len(vals)
        if n:
            breaks = np.flatnonzero(np.diff(vals.astype(np.int64)) != 1)
            run_count = len(breaks) + 1
        else:
            run_count = 0
        best = min(
            (2 * n if n <= ARRAY_MAX_SIZE else 1 << 30, CONTAINER_ARRAY),
            (2 + 4 * run_count if run_count <= RUN_MAX_SIZE else 1 << 30,
             CONTAINER_RUN),
            (8192, CONTAINER_BITMAP),
            key=lambda t: t[0],
        )
        if best[1] == CONTAINER_ARRAY:
            counts["array"] += 1
        elif best[1] == CONTAINER_RUN:
            counts["run"] += 1
        else:
            counts["bitmap"] += 1
    counts["containers"] = len(ukeys)
    return counts


# ---------------------------------------------------------------------------
# Deserialization
# ---------------------------------------------------------------------------


def _container_positions(key: int, ctype: int, card: int, data: bytes, off: int):
    base = np.uint64(key) << np.uint64(16)
    if ctype == CONTAINER_ARRAY:
        vals = np.frombuffer(data, dtype="<u2", count=card, offset=off)
        return base + vals.astype(np.uint64), off + 2 * card
    if ctype == CONTAINER_BITMAP:
        raw = np.frombuffer(data, dtype=np.uint8, count=8192, offset=off)
        bits = np.unpackbits(raw, bitorder="little")
        return base + np.flatnonzero(bits).astype(np.uint64), off + 8192
    if ctype == CONTAINER_RUN:
        (run_count,) = struct.unpack_from("<H", data, off)
        runs = np.frombuffer(
            data, dtype="<u2", count=run_count * 2, offset=off + 2
        ).reshape(-1, 2)
        parts = [
            np.arange(int(s), int(l) + 1, dtype=np.uint64) for s, l in runs
        ]
        vals = np.concatenate(parts) if parts else np.empty(0, dtype=np.uint64)
        return base + vals, off + 2 + 4 * run_count
    raise RoaringError(f"unknown container type {ctype}")


def deserialize(data: bytes) -> np.ndarray:
    """Roaring file bytes (either format) -> sorted uint64 positions,
    with any trailing Pilosa op log applied (reference
    roaring.go:1562-1654 unmarshalPilosaRoaring)."""
    return deserialize_with_opcount(data)[0]


def deserialize_with_opcount(data: bytes) -> tuple[np.ndarray, int]:
    """(positions, op-log record bit count) — the count restores a
    reopened fragment's MaxOpN snapshot trigger (the reference counts ops
    while replaying on open)."""
    if len(data) < 8:
        raise RoaringError("file too short")
    native = _native.deserialize(data)
    if native is not None:
        return native
    return _deserialize_py(data)


def _deserialize_py(data: bytes) -> tuple[np.ndarray, int]:
    (cookie,) = struct.unpack_from("<I", data, 0)
    magic = cookie & 0xFFFF
    if magic == MAGIC:
        return _deserialize_pilosa(data)
    if magic in (COOKIE_NO_RUN, COOKIE_RUN):
        return _deserialize_official(data), 0
    raise RoaringError(f"bad magic {magic}")


def _deserialize_pilosa(data: bytes) -> np.ndarray:
    (cookie, count) = struct.unpack_from("<II", data, 0)
    version = (cookie >> 16) & 0xFF
    if version != 0:
        raise RoaringError(f"unsupported storage version {version}")
    pos = 8
    keys = []
    types = []
    cards = []
    for _ in range(count):
        key, ctype, card = struct.unpack_from("<QHH", data, pos)
        keys.append(key)
        types.append(ctype)
        cards.append(card + 1)
        pos += 12
    offsets = list(struct.unpack_from(f"<{count}I", data, pos)) if count else []
    pos += 4 * count

    parts = []
    data_end = pos
    for key, ctype, card, off in zip(keys, types, cards, offsets):
        vals, end = _container_positions(key, ctype, card, data, off)
        parts.append(vals)
        data_end = max(data_end, end)
    positions = (
        np.concatenate(parts) if parts else np.empty(0, dtype=np.uint64)
    )
    # op log section
    return _apply_ops(positions, data, data_end)


def _deserialize_official(data: bytes) -> np.ndarray:
    (cookie,) = struct.unpack_from("<I", data, 0)
    magic = cookie & 0xFFFF
    pos = 4
    if magic == COOKIE_RUN:
        count = (cookie >> 16) + 1
        bitset_len = (count + 7) // 8
        run_bitset = np.unpackbits(
            np.frombuffer(data, np.uint8, bitset_len, pos), bitorder="little"
        )[:count]
        pos += bitset_len
    else:
        (count,) = struct.unpack_from("<I", data, pos)
        pos += 4
        run_bitset = np.zeros(count, dtype=np.uint8)

    keys = []
    cards = []
    for _ in range(count):
        key, card = struct.unpack_from("<HH", data, pos)
        keys.append(key)
        cards.append(card + 1)
        pos += 4
    # offset header present when no-run format or >= 4 containers
    has_offsets = magic == COOKIE_NO_RUN or count >= 4
    if has_offsets:
        offsets = list(struct.unpack_from(f"<{count}I", data, pos))
        pos += 4 * count
    else:
        offsets = None

    parts = []
    cur = pos
    for i, (key, card) in enumerate(zip(keys, cards)):
        off = offsets[i] if offsets is not None else cur
        if run_bitset[i]:
            # official run containers: [start, len] pairs (the pilosa
            # variant uses [start, last]), decoded directly here
            (run_count,) = struct.unpack_from("<H", data, off)
            runs = np.frombuffer(
                data, dtype="<u2", count=run_count * 2, offset=off + 2
            ).reshape(-1, 2)
            parts2 = [
                np.arange(int(s), int(s) + int(l) + 1, dtype=np.uint64)
                for s, l in runs
            ]
            vals = (np.uint64(key) << np.uint64(16)) + (
                np.concatenate(parts2) if parts2 else np.empty(0, np.uint64)
            )
            end = off + 2 + 4 * run_count
        else:
            ctype = CONTAINER_ARRAY if card <= ARRAY_MAX_SIZE else CONTAINER_BITMAP
            vals, end = _container_positions(key, ctype, card, data, off)
        parts.append(vals)
        cur = end
    return (
        np.concatenate(parts) if parts else np.empty(0, dtype=np.uint64)
    )


# ---------------------------------------------------------------------------
# Op log
# ---------------------------------------------------------------------------


def encode_op(op_type: int, values=None, roaring: bytes | None = None, op_n: int = 0) -> bytes:
    """One op record (reference roaring.go:4455-4503 op.WriteTo)."""
    if op_type in (OP_ADD, OP_REMOVE):
        head = struct.pack("<BQ", op_type, int(values))
        chk = _fnv32a(head)
        return head + struct.pack("<I", chk)
    if op_type in (OP_ADD_BATCH, OP_REMOVE_BATCH):
        vals = np.asarray(values, dtype="<u8")
        head = struct.pack("<BQ", op_type, len(vals))
        payload = vals.tobytes()
        chk = _fnv32a(head, payload)
        return head + struct.pack("<I", chk) + payload
    if op_type in (OP_ADD_ROARING, OP_REMOVE_ROARING):
        head = struct.pack("<BQ", op_type, len(roaring))
        tail = struct.pack("<I", op_n)
        chk = _fnv32a(head, tail, roaring)
        return head + struct.pack("<I", chk) + tail + roaring
    raise RoaringError(f"unknown op type {op_type}")


def decode_ops(data: bytes, start: int):
    """Yield (op_type, values_or_bytes, op_n) from the op-log section;
    stops at EOF or a corrupt record (reference truncates the same way)."""
    pos = start
    n = len(data)
    while pos + 13 <= n:
        op_type, value = struct.unpack_from("<BQ", data, pos)
        (chk,) = struct.unpack_from("<I", data, pos + 9)
        head = data[pos : pos + 9]
        if op_type in (OP_ADD, OP_REMOVE):
            if _fnv32a(head) != chk:
                return
            yield op_type, value, 0
            pos += 13
        elif op_type in (OP_ADD_BATCH, OP_REMOVE_BATCH):
            end = pos + 13 + value * 8
            if end > n:
                return
            payload = data[pos + 13 : end]
            if _fnv32a(head, payload) != chk:
                return
            yield op_type, np.frombuffer(payload, dtype="<u8"), 0
            pos = end
        elif op_type in (OP_ADD_ROARING, OP_REMOVE_ROARING):
            end = pos + 17 + value
            if end > n:
                return
            tail = data[pos + 13 : pos + 17]
            roaring_data = data[pos + 17 : end]
            if _fnv32a(head, tail, roaring_data) != chk:
                return
            (op_n,) = struct.unpack("<I", tail)
            yield op_type, bytes(roaring_data), op_n
            pos = end
        else:
            return


def _apply_ops(positions: np.ndarray, data: bytes, start: int) -> tuple[np.ndarray, int]:
    current: set | None = None
    op_count = 0
    for op_type, payload, op_n in decode_ops(data, start):
        if current is None:
            current = set(positions.tolist())
        if op_type == OP_ADD:
            current.add(payload)
            op_count += 1
        elif op_type == OP_REMOVE:
            current.discard(payload)
            op_count += 1
        elif op_type == OP_ADD_BATCH:
            current.update(payload.tolist())
            op_count += len(payload)
        elif op_type == OP_REMOVE_BATCH:
            current.difference_update(payload.tolist())
            op_count += len(payload)
        elif op_type == OP_ADD_ROARING:
            current.update(deserialize(payload).tolist())
            op_count += op_n
        elif op_type == OP_REMOVE_ROARING:
            current.difference_update(deserialize(payload).tolist())
            op_count += op_n
    if current is None:
        return positions, 0
    return np.array(sorted(current), dtype=np.uint64), op_count
