"""On-disk holder directory tree (reference: holder.go:134-198 Open walks
index -> field -> view -> fragment dirs; index.go:183-222 / field.go:525-548
persist .meta; attr stores in boltdb files; translate .keys log).

Layout under a data directory:

    <data>/.id                          node id (reference holder.go:599-619)
    <data>/.keys.json                   key translation store
    <data>/<index>/.meta.json           index options
    <data>/<index>/.attrs/b<block>.json column attrs, one file per 100-id
                                        block (reference boltdb buckets,
                                        boltdb/attrstore.go:37-90; a
                                        legacy whole-store .attrs.json
                                        migrates on first open)
    <data>/<index>/<field>/.meta.json   field options (+ bit depth/base)
    <data>/<index>/<field>/.attrs/      row attrs, same block layout
    <data>/<index>/<field>/views/<view>/fragments/<shard>   roaring file

Fragments attach ``FragmentFile`` stores as they are created, so every
mutation lands in an op log immediately; ``sync()`` flushes metadata, and
snapshots compact op logs in the background (SnapshotQueue).
"""

from __future__ import annotations

import json
import os
import uuid

from pilosa_tpu.core.field import Field, FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.index import Index
from pilosa_tpu.core.translate import TranslateStore
from pilosa_tpu.storage.fragmentfile import FragmentFile, SnapshotQueue
from pilosa_tpu.storage.translatelog import TranslateLog


class AttrBlocksDir:
    """Per-block attr persistence backend: one ``b<block>.json`` per
    100-id block under a directory, so a flush touches only the blocks
    that changed and reads load lazily (the BoltDB+LRU role,
    reference boltdb/attrstore.go:37-90)."""

    def __init__(self, path: str):
        self.path = path

    def _file(self, bid: int) -> str:
        return os.path.join(self.path, f"b{bid}.json")

    def load_block(self, bid: int) -> dict | None:
        try:
            with open(self._file(bid)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def block_ids(self) -> list[int]:
        try:
            names = os.listdir(self.path)
        except OSError:
            return []
        out = []
        for n in names:
            if n.startswith("b") and n.endswith(".json"):
                try:
                    out.append(int(n[1:-5]))
                except ValueError:
                    continue
        return out

    def write_blocks(self, blocks: dict[int, dict]) -> None:
        """Write (or remove, when empty) exactly the given blocks;
        tmp+rename per file so a crash never leaves a torn block."""
        if not blocks:
            return
        os.makedirs(self.path, exist_ok=True)
        for bid, data in blocks.items():
            path = self._file(bid)
            if not data:
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({str(k): v for k, v in data.items()}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)


def _attach_attr_backend(store, dir_path: str, legacy_json: str) -> None:
    """Wire an AttrStore to its block dir, migrating a legacy
    whole-store .attrs.json once."""
    store.backend = AttrBlocksDir(dir_path)
    if os.path.exists(legacy_json):
        try:
            with open(legacy_json) as f:
                legacy = json.load(f)
            # MERGE into backend-loaded blocks (set_attrs loads each
            # block through the backend first): a legacy id landing in
            # a block that already has a b<N>.json must not clobber the
            # block's other ids
            store.set_bulk_attrs(
                {int(k): dict(v) for k, v in legacy.items()}
            )
            store.flush_dirty()
            os.unlink(legacy_json)
        except (OSError, ValueError):
            pass


class HolderStore:
    """Binds a Holder to a data directory."""

    def __init__(self, holder: Holder, path: str, snapshot_workers: int = 2):
        self.holder = holder
        self.path = path
        self.translator = TranslateStore()
        self.translate_log: TranslateLog | None = None
        self.snapshot_queue = SnapshotQueue(workers=snapshot_workers)
        self._stores: list[FragmentFile] = []
        os.makedirs(path, exist_ok=True)
        holder.on_create_index = self._wire_index

    # -- paths --------------------------------------------------------------

    def _index_dir(self, index: str) -> str:
        return os.path.join(self.path, index)

    def _field_dir(self, index: str, field: str) -> str:
        return os.path.join(self.path, index, field)

    def _fragment_path(self, index: str, field: str, view: str, shard: int) -> str:
        return os.path.join(
            self._field_dir(index, field), "views", view, "fragments", str(shard)
        )

    # -- node id ------------------------------------------------------------

    def node_id(self) -> str:
        """Stable node id persisted to .id (reference holder.go:599-619)."""
        p = os.path.join(self.path, ".id")
        if os.path.exists(p):
            with open(p) as f:
                return f.read().strip()
        nid = uuid.uuid4().hex
        with open(p, "w") as f:
            f.write(nid)
        return nid

    # -- hook wiring --------------------------------------------------------

    def _wire_index(self, idx: Index) -> None:
        idx.on_create_field = self._wire_field
        for f in idx.fields.values():
            self._wire_field(idx, f)

    def _wire_field(self, idx: Index, field: Field) -> None:
        def on_fragment(view, shard):
            frag = view.fragments[shard]
            if frag.store is not None:
                return
            path = self._fragment_path(idx.name, field.name, view.name, shard)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            store = FragmentFile(
                frag, path, self.snapshot_queue,
                journal=self.holder.events,
            )
            store.open()
            self._stores.append(store)

        field.on_create_fragment = on_fragment
        for view in field.views.values():
            view.on_create_fragment = on_fragment
            for shard, frag in view.fragments.items():
                if frag.store is None:
                    on_fragment(view, shard)

    # -- open/sync/close ----------------------------------------------------

    def open(self) -> None:
        """Walk the directory tree, rebuild schema + load every fragment
        (reference holder.go:134-198)."""
        # Key translation: append-only log (reference translate.go
        # TranslateFile .keys). A legacy .keys.json snapshot migrates into
        # the log on first open.
        legacy_path = os.path.join(self.path, ".keys.json")
        legacy = None
        if os.path.exists(legacy_path):
            with open(legacy_path) as f:
                legacy = json.load(f)
        self.translate_log = TranslateLog(
            self.translator, os.path.join(self.path, ".keys")
        )
        self.translate_log.open()
        if legacy is not None:
            # Migrate the legacy snapshot into the log, skipping mappings
            # the log replay already installed — a crash between append and
            # os.remove must not duplicate the whole snapshot on the next
            # open (replay is idempotent, but the log would grow unboundedly
            # across crash loops).
            replayed = self.translator.to_dict()
            for joined, key_list in legacy.items():
                index, _, field = joined.partition("|")
                have = replayed.get(joined, [])
                keys = [k for k in key_list if k != ""]
                ids = [i + 1 for i, k in enumerate(key_list) if k != ""]
                missing_k = []
                missing_i = []
                for k, i in zip(keys, ids):
                    if i > len(have) or have[i - 1] != k:
                        missing_k.append(k)
                        missing_i.append(i)
                # set_mapping installs in memory and (via on_insert, hooked
                # by translate_log.open) appends only the missing records.
                if missing_k:
                    self.translator.set_mapping(
                        index, field, missing_k, missing_i
                    )
            os.remove(legacy_path)
        for index_name in sorted(os.listdir(self.path)):
            index_dir = self._index_dir(index_name)
            meta_path = os.path.join(index_dir, ".meta.json")
            if not os.path.isdir(index_dir) or not os.path.exists(meta_path):
                continue
            with open(meta_path) as f:
                meta = json.load(f)
            idx = self.holder.create_index_if_not_exists(
                index_name,
                keys=meta.get("keys", False),
                track_existence=meta.get("trackExistence", True),
            )
            _attach_attr_backend(
                idx.column_attrs,
                os.path.join(index_dir, ".attrs"),
                os.path.join(index_dir, ".attrs.json"),
            )
            for field_name in sorted(os.listdir(index_dir)):
                field_dir = self._field_dir(index_name, field_name)
                fmeta_path = os.path.join(field_dir, ".meta.json")
                if not os.path.isdir(field_dir) or not os.path.exists(fmeta_path):
                    continue
                with open(fmeta_path) as f:
                    fmeta = json.load(f)
                if field_name in idx.fields:
                    field = idx.fields[field_name]
                else:
                    field = idx.create_field(
                        field_name, FieldOptions.from_dict(fmeta.get("options", {}))
                    )
                field.base = fmeta.get("base", field.base)
                field.bit_depth = fmeta.get("bitDepth", field.bit_depth)
                _attach_attr_backend(
                    field.row_attrs,
                    os.path.join(field_dir, ".attrs"),
                    os.path.join(field_dir, ".attrs.json"),
                )
                views_dir = os.path.join(field_dir, "views")
                if os.path.isdir(views_dir):
                    for view_name in sorted(os.listdir(views_dir)):
                        frags_dir = os.path.join(views_dir, view_name, "fragments")
                        if not os.path.isdir(frags_dir):
                            continue
                        view = field.create_view_if_not_exists(view_name)
                        for shard_name in sorted(os.listdir(frags_dir)):
                            if not shard_name.isdigit():
                                continue
                            view.create_fragment_if_not_exists(int(shard_name))
        # wire hooks for everything that exists (loads fragments) and
        # everything created later
        for idx in self.holder.indexes.values():
            self._wire_index(idx)
        self.holder.on_create_index = self._wire_index

    def sync(self) -> None:
        """Flush schema, attrs, and translation to disk (fragment data is
        already durable via op logs; key translation via its own log)."""
        if self.translate_log is not None:
            self.translate_log.sync()
        for idx in self.holder.indexes.values():
            index_dir = self._index_dir(idx.name)
            os.makedirs(index_dir, exist_ok=True)
            with open(os.path.join(index_dir, ".meta.json"), "w") as f:
                json.dump(
                    {"keys": idx.keys, "trackExistence": idx.track_existence}, f
                )
            self._flush_attrs(
                idx.column_attrs, os.path.join(index_dir, ".attrs")
            )
            for field in idx.fields.values():
                field_dir = self._field_dir(idx.name, field.name)
                os.makedirs(field_dir, exist_ok=True)
                with open(os.path.join(field_dir, ".meta.json"), "w") as f:
                    json.dump(
                        {
                            "options": field.options.to_dict(),
                            "base": field.base,
                            "bitDepth": field.bit_depth,
                        },
                        f,
                    )
                self._flush_attrs(
                    field.row_attrs, os.path.join(field_dir, ".attrs")
                )

    @staticmethod
    def _flush_attrs(store, dir_path: str) -> None:
        """Write only the blocks dirtied since the last flush (no
        whole-store rewrite — reference boltdb writes per bucket)."""
        if store.backend is None:
            store.backend = AttrBlocksDir(dir_path)
        store.flush_dirty()

    def _detach_stores(self, match) -> None:
        """Close + drop FragmentFile stores whose fragment matches, so
        deleted indexes/fields leak neither fds nor _stores entries."""
        kept = []
        for store in self._stores:
            if match(store.fragment):
                store.close()
                store.fragment.store = None
            else:
                kept.append(store)
        self._stores = kept

    def delete_index_dir(self, name: str) -> None:
        import shutil

        self._detach_stores(lambda frag: frag.index == name)
        d = self._index_dir(name)
        if os.path.isdir(d):
            shutil.rmtree(d)

    def delete_fragment(self, index: str, field: str, view: str, shard: int) -> None:
        """Detach + delete one fragment's backing file (resize cleanup,
        reference holderCleaner holder.go:898-926)."""
        self._detach_stores(
            lambda frag: frag.index == index
            and frag.field == field
            and frag.view == view
            and frag.shard == shard
        )
        p = self._fragment_path(index, field, view, shard)
        if os.path.exists(p):
            os.remove(p)

    def delete_field_dir(self, index: str, name: str) -> None:
        import shutil

        self._detach_stores(
            lambda frag: frag.index == index and frag.field == name
        )
        d = self._field_dir(index, name)
        if os.path.isdir(d):
            shutil.rmtree(d)

    def close(self) -> None:
        self.sync()
        if self.translate_log is not None:
            self.translate_log.close()
        self.snapshot_queue.await_all()
        self.snapshot_queue.stop()
        for store in self._stores:
            store.close()
