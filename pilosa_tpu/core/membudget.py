"""Device-memory budget: tiered working-set accounting for HBM copies.

The reference caps mmap count / open files and raises rlimits so a holder
with more fragments than the OS allows still serves (reference
syswrap/mmap.go — 60k map cap with file fallback; holder.go:43,551-597).
The TPU analogue is HBM: every fragment device copy and every executor
field stack is registered here, and when the budget cap is exceeded cold
entries are evicted back to their host mirrors (the "file fallback").
Device memory is per-process, not per-Holder, so the default budget is a
process-wide singleton; tests or embedders can configure a small cap to
exercise eviction.

Eviction policy — clock over LRU, with pinning:

* entries keep LRU order (``touch`` moves to the tail), and every touch
  also sets a *reference bit*;
* the eviction scan walks from the LRU head; a referenced entry gets a
  second chance (bit cleared, moved to the tail) instead of being
  evicted — an entry that was hit since the last scan is never the one
  that pays for a one-off large admit;
* **pinned** entries are skipped entirely: the residency tracker
  (core/residency.py) pins hot fragments and the executor pins hot field
  stacks, so the zipfian head of a working set survives eviction storms
  from its own tail.  Pinned bytes are capped at ``PIN_MAX_FRACTION`` of
  the budget so the scan always has victims to find.

Deadlock discipline: evict callbacks are invoked AFTER the budget lock is
released (victims are collected under the lock, called outside it), so a
callback may take its owner's lock while the admit path holds
owner-lock -> budget-lock — the two orders never nest.
"""

from __future__ import annotations

import os
import threading
import weakref
from collections import OrderedDict
from typing import Callable

# A pinned working set may not squat on the whole budget: the eviction
# scan must always be able to find victims, so pin() declines once
# pinned bytes would exceed this fraction of the cap.
PIN_MAX_FRACTION = 0.5


class _Entry:
    """One admitted allocation: bytes, evict callback, clock state."""

    __slots__ = ("nbytes", "evict", "pinned", "ref")

    def __init__(self, nbytes: int, evict: Callable[[], None]):
        self.nbytes = nbytes
        self.evict = evict
        self.pinned = False
        self.ref = False


class DeviceBudget:
    """Tracks device-resident bytes per owner key with clock/LRU
    eviction and pinning."""

    def __init__(self, cap_bytes: int | None = None):
        self.cap = cap_bytes  # None = unlimited (accounting only)
        self._lock = threading.Lock()
        # key -> _Entry; insertion order = LRU order (head = coldest)
        self._entries: "OrderedDict[object, _Entry]" = OrderedDict()
        self._used = 0
        self._pinned_bytes = 0
        # counters for stats/diagnostics
        self.evictions = 0
        self.admissions = 0
        self.evict_errors = 0
        # residency counters: an admit of an absent key paid an upload
        # (miss); a touch found the bytes already resident (hit)
        self.hits = 0
        self.misses = 0
        self.pins = 0
        self.unpins = 0
        self.pin_declined = 0

    def used(self) -> int:
        with self._lock:
            return self._used

    def entry_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def pinned_bytes(self) -> int:
        with self._lock:
            return self._pinned_bytes

    def snapshot(self) -> dict:
        """One consistent view for /metrics and /debug/vars."""
        with self._lock:
            return {
                "usedBytes": self._used,
                "capBytes": self.cap,
                "entries": len(self._entries),
                "evictions": self.evictions,
                "admissions": self.admissions,
                "evictErrors": self.evict_errors,
                "hits": self.hits,
                "misses": self.misses,
                "pins": self.pins,
                "unpins": self.unpins,
                "pinDeclined": self.pin_declined,
                "pinnedEntries": sum(
                    1 for e in self._entries.values() if e.pinned
                ),
                "pinnedBytes": self._pinned_bytes,
            }

    def would_decline(self, nbytes: int) -> bool:
        """True when a single allocation of ``nbytes`` exceeds the whole
        cap — callers should prefer a paged strategy over admitting it."""
        return self.cap is not None and nbytes > self.cap

    def _collect_victims(self, needed: int) -> list[Callable[[], None]]:
        """Clock scan from the LRU head (caller holds the lock): pinned
        entries are skipped, referenced entries get a second chance, the
        rest are evicted until ``needed`` more bytes fit under the cap.
        Bounded at two full cycles: the first clears every reference
        bit, so the second finds a victim or proves everything left is
        pinned."""
        victims: list[Callable[[], None]] = []
        scans = 2 * len(self._entries)
        while self._used + needed > self.cap and self._entries and scans > 0:
            scans -= 1
            key, entry = next(iter(self._entries.items()))
            if entry.pinned:
                self._entries.move_to_end(key)
                if all(e.pinned for e in self._entries.values()):
                    break  # nothing evictable; admit over cap
                continue
            if entry.ref:
                entry.ref = False  # second chance
                self._entries.move_to_end(key)
                continue
            self._entries.popitem(last=False)
            self._used -= entry.nbytes
            self.evictions += 1
            victims.append(entry.evict)
        return victims

    def admit(self, key, nbytes: int, evict: Callable[[], None]) -> None:
        """Account ``nbytes`` of device memory for ``key`` (replacing any
        previous entry), evicting cold OTHER entries until the cap is
        met.  An entry larger than the entire cap is still admitted
        after evicting everything evictable — the caller already holds
        the array; callers that can page should check ``would_decline``
        first."""
        victims: list[Callable[[], None]] = []
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._used -= old.nbytes
                if old.pinned:
                    self._pinned_bytes -= old.nbytes
            else:
                self.misses += 1
            if self.cap is not None:
                victims = self._collect_victims(nbytes)
            entry = _Entry(nbytes, evict)
            # arrive with the reference bit set: a freshly staged entry
            # (often a predictive prefetch whose consumer hasn't run yet)
            # survives one scan cycle instead of being the next admit's
            # victim — classic CLOCK "insert behind the hand"
            entry.ref = True
            if old is not None and old.pinned:
                # a pinned owner re-admitting (e.g. capacity grow) stays
                # pinned — the heat that earned the pin didn't reset
                entry.pinned = True
                self._pinned_bytes += nbytes
            self._entries[key] = entry
            self._used += nbytes
            self.admissions += 1
        for cb in victims:
            try:
                cb()
            except Exception:
                # eviction is advisory; owner may already be gone —
                # counted so a flaky callback is visible in diagnostics
                self.evict_errors += 1

    def set_cap(self, cap_bytes: int | None) -> None:
        """Change the cap IN PLACE, keeping every entry's accounting.
        Shrinking below current use evicts cold unpinned entries (their
        callbacks run, so owners drop device copies and re-admit on next
        sync) — the online oversubscription knob: unlike ``configure``,
        resident state is trimmed, not forgotten.  Pins granted under a
        larger (or absent) cap are re-validated first: coldest pinned
        entries are shed until pinned bytes fit ``PIN_MAX_FRACTION`` of
        the new cap, restoring the invariant that the clock scan always
        has victims (heat re-pins what still deserves it)."""
        victims: list[Callable[[], None]] = []
        with self._lock:
            self.cap = cap_bytes
            if self.cap is not None:
                limit = self.cap * PIN_MAX_FRACTION
                for key, entry in list(self._entries.items()):
                    if self._pinned_bytes <= limit:
                        break
                    if entry.pinned:  # LRU head first: coldest pin goes
                        entry.pinned = False
                        self._pinned_bytes -= entry.nbytes
                        self.unpins += 1
                victims = self._collect_victims(0)
        for cb in victims:
            try:
                cb()
            except Exception:
                self.evict_errors += 1

    def touch(self, key) -> None:
        """Use stamp: LRU move-to-tail plus the clock reference bit."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                entry.ref = True
                self.hits += 1

    def pin(self, key) -> bool:
        """Exempt ``key`` from eviction.  Declines (False) when the key
        is absent or when pinning it would push pinned bytes past
        ``PIN_MAX_FRACTION`` of the cap — the scan must keep victims."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return False
            if entry.pinned:
                return True
            if (
                self.cap is not None
                and self._pinned_bytes + entry.nbytes > self.cap * PIN_MAX_FRACTION
            ):
                self.pin_declined += 1
                return False
            entry.pinned = True
            self._pinned_bytes += entry.nbytes
            self.pins += 1
            return True

    def unpin(self, key) -> bool:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None or not entry.pinned:
                return False
            entry.pinned = False
            self._pinned_bytes -= entry.nbytes
            self.unpins += 1
            return True

    def is_pinned(self, key) -> bool:
        with self._lock:
            entry = self._entries.get(key)
            return entry is not None and entry.pinned

    def release(self, key) -> None:
        """Remove an entry WITHOUT invoking its evict callback (the owner
        dropped its device copy itself, or died)."""
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._used -= old.nbytes
                if old.pinned:
                    self._pinned_bytes -= old.nbytes


_default: DeviceBudget | None = None
_default_lock = threading.Lock()

# Fraction of the accelerator's reported bytes_limit used when no explicit
# cap is configured: stacks/fragments may not squat on ALL of HBM — XLA
# needs headroom for program temporaries (gram staging, scan buffers).
DEFAULT_HBM_FRACTION = 0.8


def _probe_device_cap() -> int | None:
    """Derive a default cap from the local accelerator's memory stats
    (reference ships working syswrap defaults — 60k maps,
    syswrap/mmap.go — rather than unlimited).  None on CPU backends or
    when the runtime exposes no stats."""
    try:
        import jax

        dev = jax.local_devices()[0]
        if dev.platform == "cpu":
            return None
        stats = dev.memory_stats() or {}
        limit = stats.get("bytes_limit")
        if not limit:
            return None
        return int(limit * DEFAULT_HBM_FRACTION)
    except Exception:
        return None


def default_budget() -> DeviceBudget:
    """The process-wide budget.  Cap precedence: explicit
    PILOSA_TPU_HBM_BUDGET_BYTES (0 = force unlimited accounting), else
    80% of the accelerator's ``bytes_limit`` (a real v5e would OOM on
    device allocations long before an unlimited LRU ever engaged), else
    unlimited on CPU."""
    global _default
    with _default_lock:
        if _default is None:
            env = os.environ.get("PILOSA_TPU_HBM_BUDGET_BYTES")
            if env is not None:
                cap = int(env) or None
            else:
                cap = _probe_device_cap()
            _default = DeviceBudget(cap)
        return _default


def configure(cap_bytes: int | None) -> DeviceBudget:
    """Install a fresh process-wide budget with the given cap (existing
    entries are forgotten, not evicted — their owners re-admit on next
    device sync)."""
    global _default
    with _default_lock:
        _default = DeviceBudget(cap_bytes)
        return _default


def set_cap(cap_bytes: int | None) -> DeviceBudget:
    """Change the process-wide budget's cap in place (entries kept,
    excess evicted) — see ``DeviceBudget.set_cap``.  The load harness's
    stage-scoped ``device_budget`` rides this so an oversubscribed stage
    squeezes the live working set instead of starting a blank ledger."""
    budget = default_budget()
    budget.set_cap(cap_bytes)
    return budget


def register_owner(key_obj, budget: DeviceBudget) -> object:
    """A stable budget key for ``key_obj`` that auto-releases its entry
    when the owner is garbage collected."""
    key = id(key_obj)
    weakref.finalize(key_obj, budget.release, key)
    return key
