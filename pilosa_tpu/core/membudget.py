"""Device-memory budget: LRU accounting for HBM-resident copies.

The reference caps mmap count / open files and raises rlimits so a holder
with more fragments than the OS allows still serves (reference
syswrap/mmap.go — 60k map cap with file fallback; holder.go:43,551-597).
The TPU analogue is HBM: every fragment device copy and every executor
field stack is registered here, and when the budget cap is exceeded the
least-recently-used entries are evicted back to their host mirrors (the
"file fallback").  Device memory is per-process, not per-Holder, so the
default budget is a process-wide singleton; tests or embedders can
configure a small cap to exercise eviction.

Deadlock discipline: evict callbacks are invoked AFTER the budget lock is
released (victims are collected under the lock, called outside it), so a
callback may take its owner's lock while the admit path holds
owner-lock -> budget-lock — the two orders never nest.
"""

from __future__ import annotations

import os
import threading
import weakref
from collections import OrderedDict
from typing import Callable


class DeviceBudget:
    """Tracks device-resident bytes per owner key with LRU eviction."""

    def __init__(self, cap_bytes: int | None = None):
        self.cap = cap_bytes  # None = unlimited (accounting only)
        self._lock = threading.Lock()
        # key -> (nbytes, evict_callback); insertion order = LRU order
        self._entries: "OrderedDict[object, tuple[int, Callable[[], None]]]" = (
            OrderedDict()
        )
        self._used = 0
        # counters for stats/diagnostics
        self.evictions = 0
        self.admissions = 0
        self.evict_errors = 0

    def used(self) -> int:
        with self._lock:
            return self._used

    def entry_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> dict:
        """One consistent view for /metrics and /debug/vars."""
        with self._lock:
            return {
                "usedBytes": self._used,
                "capBytes": self.cap,
                "entries": len(self._entries),
                "evictions": self.evictions,
                "admissions": self.admissions,
                "evictErrors": self.evict_errors,
            }

    def would_decline(self, nbytes: int) -> bool:
        """True when a single allocation of ``nbytes`` exceeds the whole
        cap — callers should prefer a paged strategy over admitting it."""
        return self.cap is not None and nbytes > self.cap

    def admit(self, key, nbytes: int, evict: Callable[[], None]) -> None:
        """Account ``nbytes`` of device memory for ``key`` (replacing any
        previous entry), evicting least-recently-used OTHER entries until
        the cap is met.  An entry larger than the entire cap is still
        admitted after evicting everything else — the caller already
        holds the array; callers that can page should check
        ``would_decline`` first."""
        victims: list[Callable[[], None]] = []
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._used -= old[0]
            if self.cap is not None:
                while self._used + nbytes > self.cap and self._entries:
                    _, (vbytes, vcb) = self._entries.popitem(last=False)
                    self._used -= vbytes
                    self.evictions += 1
                    victims.append(vcb)
            self._entries[key] = (nbytes, evict)
            self._used += nbytes
            self.admissions += 1
        for cb in victims:
            try:
                cb()
            except Exception:
                # eviction is advisory; owner may already be gone —
                # counted so a flaky callback is visible in diagnostics
                self.evict_errors += 1

    def touch(self, key) -> None:
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)

    def release(self, key) -> None:
        """Remove an entry WITHOUT invoking its evict callback (the owner
        dropped its device copy itself, or died)."""
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._used -= old[0]


_default: DeviceBudget | None = None
_default_lock = threading.Lock()

# Fraction of the accelerator's reported bytes_limit used when no explicit
# cap is configured: stacks/fragments may not squat on ALL of HBM — XLA
# needs headroom for program temporaries (gram staging, scan buffers).
DEFAULT_HBM_FRACTION = 0.8


def _probe_device_cap() -> int | None:
    """Derive a default cap from the local accelerator's memory stats
    (reference ships working syswrap defaults — 60k maps,
    syswrap/mmap.go — rather than unlimited).  None on CPU backends or
    when the runtime exposes no stats."""
    try:
        import jax

        dev = jax.local_devices()[0]
        if dev.platform == "cpu":
            return None
        stats = dev.memory_stats() or {}
        limit = stats.get("bytes_limit")
        if not limit:
            return None
        return int(limit * DEFAULT_HBM_FRACTION)
    except Exception:
        return None


def default_budget() -> DeviceBudget:
    """The process-wide budget.  Cap precedence: explicit
    PILOSA_TPU_HBM_BUDGET_BYTES (0 = force unlimited accounting), else
    80% of the accelerator's ``bytes_limit`` (a real v5e would OOM on
    device allocations long before an unlimited LRU ever engaged), else
    unlimited on CPU."""
    global _default
    with _default_lock:
        if _default is None:
            env = os.environ.get("PILOSA_TPU_HBM_BUDGET_BYTES")
            if env is not None:
                cap = int(env) or None
            else:
                cap = _probe_device_cap()
            _default = DeviceBudget(cap)
        return _default


def configure(cap_bytes: int | None) -> DeviceBudget:
    """Install a fresh process-wide budget with the given cap (existing
    entries are forgotten, not evicted — their owners re-admit on next
    device sync)."""
    global _default
    with _default_lock:
        _default = DeviceBudget(cap_bytes)
        return _default


def register_owner(key_obj, budget: DeviceBudget) -> object:
    """A stable budget key for ``key_obj`` that auto-releases its entry
    when the owner is garbage collected."""
    key = id(key_obj)
    weakref.finalize(key_obj, budget.release, key)
    return key
