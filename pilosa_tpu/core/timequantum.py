"""Time quantums and time-based view naming (reference: time.go).

A time field materializes extra views per time unit: ``standard_2017``,
``standard_201701``, ``standard_20170102``, ``standard_2017010203``
(reference time.go:75-101). A range query decomposes [start, end) into a
minimal cover of pre-materialized views by walking up from small units to
large and back down (reference time.go:104-176 viewsByTimeRange).
"""

from __future__ import annotations

from datetime import datetime, timedelta

TIME_FORMAT = "%Y-%m-%dT%H:%M"

VALID_QUANTUMS = {"Y", "YM", "YMD", "YMDH", "M", "MD", "MDH", "D", "DH", "H", ""}


def valid_quantum(q: str) -> bool:
    """reference time.go:44-55."""
    return q in VALID_QUANTUMS


def parse_time(t) -> datetime:
    """Parse a PQL timestamp string or unix seconds (reference
    time.go:220-234)."""
    if isinstance(t, str):
        try:
            return datetime.strptime(t, TIME_FORMAT)
        except ValueError as e:
            raise ValueError("cannot parse string time") from e
    if isinstance(t, int) and not isinstance(t, bool):
        return datetime.utcfromtimestamp(t)
    raise ValueError("arg must be a timestamp")


def view_by_time_unit(name: str, t: datetime, unit: str) -> str:
    """reference time.go:75-88."""
    fmt = {"Y": "%Y", "M": "%Y%m", "D": "%Y%m%d", "H": "%Y%m%d%H"}.get(unit)
    if fmt is None:
        return ""
    return f"{name}_{t.strftime(fmt)}"


def views_by_time(name: str, t: datetime, quantum: str) -> list[str]:
    """All unit views a timestamped bit lands in (reference time.go:91-101)."""
    return [
        v for u in quantum if (v := view_by_time_unit(name, t, u))
    ]


def _add_year(t: datetime) -> datetime:
    return t.replace(year=t.year + 1)


def _add_month(t: datetime) -> datetime:
    """reference time.go:183-189 addMonth: clamp to day 1 for days >28 to
    avoid double-month hops (Jan 31 + 1mo = Mar 2)."""
    if t.day > 28:
        t = t.replace(day=1)
    if t.month == 12:
        return t.replace(year=t.year + 1, month=1)
    return t.replace(month=t.month + 1)


def _next_year_gte(t: datetime, end: datetime) -> bool:
    nxt = _add_year(t)
    return nxt.year == end.year or end > nxt


def _next_month_gte(t: datetime, end: datetime) -> bool:
    nxt = _add_month_exact(t)
    return (nxt.year, nxt.month) == (end.year, end.month) or end > nxt


def _add_month_exact(t: datetime) -> datetime:
    """time.AddDate(0,1,0) equivalent with Go's normalization (Jan 31 ->
    Mar 2/3)."""
    month = t.month + 1
    year = t.year
    if month > 12:
        month = 1
        year += 1
    day = t.day
    # Go normalizes out-of-range days by rolling into the next month.
    while True:
        try:
            return t.replace(year=year, month=month, day=day)
        except ValueError:
            # emulate normalization: day 31 in a 30-day month -> day 1 + 1mo
            days_in = (_first_of_next(year, month) - timedelta(days=1)).day
            overflow = day - days_in
            t2 = t.replace(year=year, month=month, day=days_in) + timedelta(
                days=overflow
            )
            return t2


def _first_of_next(year: int, month: int) -> datetime:
    if month == 12:
        return datetime(year + 1, 1, 1)
    return datetime(year, month + 1, 1)


def _next_day_gte(t: datetime, end: datetime) -> bool:
    nxt = t + timedelta(days=1)
    return (nxt.year, nxt.month, nxt.day) == (end.year, end.month, end.day) or end > nxt


def views_by_time_range(name: str, start: datetime, end: datetime, quantum: str) -> list[str]:
    """Minimal view cover of [start, end) (reference time.go:104-176)."""
    has_year = "Y" in quantum
    has_month = "M" in quantum
    has_day = "D" in quantum
    has_hour = "H" in quantum

    t = start
    results: list[str] = []

    # Walk up from smallest units to largest.
    if has_hour or has_day or has_month:
        while t < end:
            if has_hour:
                if not _next_day_gte(t, end):
                    break
                elif t.hour != 0:
                    results.append(view_by_time_unit(name, t, "H"))
                    t = t + timedelta(hours=1)
                    continue
            if has_day:
                if not _next_month_gte(t, end):
                    break
                elif t.day != 1:
                    results.append(view_by_time_unit(name, t, "D"))
                    t = t + timedelta(days=1)
                    continue
            if has_month:
                if not _next_year_gte(t, end):
                    break
                elif t.month != 1:
                    results.append(view_by_time_unit(name, t, "M"))
                    t = _add_month(t)
                    continue
            break

    # Walk back down from largest units to smallest.
    while t < end:
        if has_year and _next_year_gte(t, end):
            results.append(view_by_time_unit(name, t, "Y"))
            t = _add_year(t)
        elif has_month and _next_month_gte(t, end):
            results.append(view_by_time_unit(name, t, "M"))
            # clamped advance (reference time.go:144,162 use addMonth, not
            # AddDate) so Jan 31 + 1mo lands in February, not March
            t = _add_month(t)
        elif has_day and _next_day_gte(t, end):
            results.append(view_by_time_unit(name, t, "D"))
            t = t + timedelta(days=1)
        elif has_hour:
            results.append(view_by_time_unit(name, t, "H"))
            t = t + timedelta(hours=1)
        else:
            break

    return results


def view_time_part(view: str) -> str:
    """reference time.go:331-334."""
    return view.rsplit("_", 1)[-1]


def min_max_views(views: list[str], quantum: str) -> tuple[str, str]:
    """Min/max same-granularity views (reference time.go:240-274)."""
    views = sorted(views)
    if "Y" in quantum:
        chars = 4
    elif "M" in quantum:
        chars = 6
    elif "D" in quantum:
        chars = 8
    elif "H" in quantum:
        chars = 10
    else:
        chars = 0
    lo = next((v for v in views if len(view_time_part(v)) == chars), "")
    hi = next((v for v in reversed(views) if len(view_time_part(v)) == chars), "")
    return lo, hi


def time_of_view(view: str, adj: bool) -> datetime | None:
    """Start time of a view's period; end when ``adj`` (reference
    time.go:279-327)."""
    if not view:
        return None
    part = view_time_part(view)
    n = len(part)
    if n == 4:
        t = datetime.strptime(part, "%Y")
        return _add_year(t) if adj else t
    if n == 6:
        t = datetime.strptime(part, "%Y%m")
        return _add_month(t) if adj else t
    if n == 8:
        t = datetime.strptime(part, "%Y%m%d")
        return t + timedelta(days=1) if adj else t
    if n == 10:
        t = datetime.strptime(part, "%Y%m%d%H")
        return t + timedelta(hours=1) if adj else t
    raise ValueError(f"invalid time format on view: {view}")


def view_cover(field, from_arg, to_arg, standard_name: str) -> list[str] | None:
    """The minimal time-view cover of [from, to] for a field, clamping a
    missing bound to the field's existing time views (reference
    executor.go:1376-1397 + time.go viewsByTimeRange).  None when a bound
    is missing and no time views exist (the range is provably empty).
    Raises ValueError when the field has no time quantum."""
    q = field.options.time_quantum
    if not q:
        raise ValueError(
            f"field {field.name!r} has no time quantum for time range"
        )
    start = parse_time(from_arg) if from_arg is not None else None
    end = parse_time(to_arg) if to_arg is not None else None
    if start is None or end is None:
        time_views = [
            v for v in field.views if v.startswith(standard_name + "_")
        ]
        lo_v, hi_v = min_max_views(time_views, q)
        if start is None:
            if not lo_v:
                return None
            start = time_of_view(lo_v, False)
        if end is None:
            if not hi_v:
                return None
            end = time_of_view(hi_v, True)
    return views_by_time_range(standard_name, start, end, q)
