"""Field: a typed container of views (reference: field.go).

Field types (reference field.go:55-61): ``set`` (default, multi-row
bitmap), ``int`` (BSI range-encoded), ``time`` (set + time-quantum views),
``mutex`` (one row per column), ``bool`` (two rows). Options mirror
reference field.go:1374-1385: keys, cacheType/cacheSize, min/max (int),
timeQuantum, noStandardView.
"""

from __future__ import annotations

import re
import threading
from datetime import datetime
from typing import Iterable

import numpy as np

from pilosa_tpu.core import timequantum
from pilosa_tpu.core.attrs import AttrStore
from pilosa_tpu.core.view import VIEW_STANDARD, View, view_name_bsi
from pilosa_tpu.obs import stats as stats_mod
from pilosa_tpu.obs import tracing
from pilosa_tpu.shardwidth import SHARD_WORDS

FIELD_TYPE_SET = "set"
FIELD_TYPE_INT = "int"
FIELD_TYPE_TIME = "time"
FIELD_TYPE_MUTEX = "mutex"
FIELD_TYPE_BOOL = "bool"

# reference field.go:44-47 defaults.
DEFAULT_CACHE_TYPE = "ranked"
DEFAULT_CACHE_SIZE = 50000

# bool fields store false/true in rows 0/1 (reference field.go:49-53
# falseRowID/trueRowID).
FALSE_ROW_ID = 0
TRUE_ROW_ID = 1

_NAME_RE = re.compile(r"^[a-z][a-z0-9_-]{0,63}$")


def validate_name(name: str) -> None:
    """reference field.go validateName / index.go (lowercase, 64 chars)."""
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid name: {name!r}")


def bit_depth_of(v: int) -> int:
    """Bits required to store abs(v) (reference field.go:1606-1621)."""
    v = abs(v)
    for i in range(64):
        if v < (1 << i):
            return i
    return 63


class FieldOptions:
    """reference field.go:1374-1385 FieldOptions."""

    def __init__(
        self,
        field_type: str = FIELD_TYPE_SET,
        keys: bool = False,
        cache_type: str = DEFAULT_CACHE_TYPE,
        cache_size: int = DEFAULT_CACHE_SIZE,
        min_: int = 0,
        max_: int = 0,
        time_quantum: str = "",
        no_standard_view: bool = False,
    ):
        self.field_type = field_type
        self.keys = keys
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.min = min_
        self.max = max_
        self.time_quantum = time_quantum
        self.no_standard_view = no_standard_view

    def to_dict(self) -> dict:
        return {
            "type": self.field_type,
            "keys": self.keys,
            "cacheType": self.cache_type,
            "cacheSize": self.cache_size,
            "min": self.min,
            "max": self.max,
            "timeQuantum": self.time_quantum,
            "noStandardView": self.no_standard_view,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FieldOptions":
        return cls(
            field_type=d.get("type", FIELD_TYPE_SET),
            keys=d.get("keys", False),
            cache_type=d.get("cacheType", DEFAULT_CACHE_TYPE),
            cache_size=d.get("cacheSize", DEFAULT_CACHE_SIZE),
            min_=d.get("min", 0),
            max_=d.get("max", 0),
            time_quantum=d.get("timeQuantum", ""),
            no_standard_view=d.get("noStandardView", False),
        )


class Field:
    """reference field.go:64 Field."""

    def __init__(
        self,
        index: str,
        name: str,
        options: FieldOptions | None = None,
        n_words: int = SHARD_WORDS,
    ):
        # Internal fields (e.g. "_exists") bypass user-name validation
        # (reference holder.go:46).
        if not name.startswith("_"):
            validate_name(name)
        self.index = index
        self.name = name
        self.options = options or FieldOptions()
        self.n_words = n_words
        self._lock = threading.RLock()
        self.views: dict[str, View] = {}
        # row attributes (reference field.go rowAttrStore)
        self.row_attrs = AttrStore()
        self.on_create_view = None  # cluster broadcast hook (field.go:795-815)
        self.on_create_fragment = None
        # Shards held by OTHER nodes, learned via create-shard broadcasts
        # (reference field.go:263-345 remoteAvailableShards).
        self.remote_available_shards: set[int] = set()
        # Metrics sink, tagged index:/field: by the creation chain
        # (reference fragment.go:714 SetBit/ClearBit counts).
        self.stats = stats_mod.NOP

        o = self.options
        if o.field_type == FIELD_TYPE_INT:
            if o.min > o.max:
                raise ValueError("invalid int field range")
            # Base offsets stored values so the common case (all-positive
            # ranges) uses minimal bit depth (reference field.go bsiGroup
            # Base; v2 BSI).
            self.base = o.min if o.min > 0 else (o.max if o.max < 0 else 0)
            self.bit_depth = max(
                bit_depth_of(o.min - self.base), bit_depth_of(o.max - self.base)
            )
        else:
            self.base = 0
            self.bit_depth = 0
        if o.field_type == FIELD_TYPE_TIME and not timequantum.valid_quantum(
            o.time_quantum
        ):
            raise ValueError("invalid time quantum")

    # -- type predicates ----------------------------------------------------

    @property
    def field_type(self) -> str:
        return self.options.field_type

    @property
    def keys(self) -> bool:
        return self.options.keys

    def is_bsi(self) -> bool:
        return self.field_type == FIELD_TYPE_INT

    # -- views --------------------------------------------------------------

    def view(self, name: str) -> View | None:
        return self.views.get(name)

    def create_view_if_not_exists(self, name: str) -> View:
        with self._lock:
            v = self.views.get(name)
            if v is None:
                v = View(self.index, self.name, name, self.n_words)
                v.on_create_fragment = self.on_create_fragment
                self.views[name] = v
                if self.on_create_view is not None:
                    self.on_create_view(self, name)
            return v

    def view_names(self) -> list[str]:
        return sorted(self.views)

    def delete_view(self, name: str) -> bool:
        with self._lock:
            return self.views.pop(name, None) is not None

    def bsi_view_name(self) -> str:
        return view_name_bsi(self.name)

    def available_shards(self) -> set[int]:
        """Union of local shards across views plus shards known to exist
        on other nodes (reference field.go remoteAvailableShards + local)."""
        shards: set[int] = set(self.remote_available_shards)
        for v in self.views.values():
            shards |= v.available_shards()
        return shards

    def add_remote_available_shards(self, shards) -> None:
        """Merge shards learned from a create-shard broadcast or node
        status exchange (reference field.go:331-345 AddRemoteAvailableShards)."""
        with self._lock:
            self.remote_available_shards |= set(shards)

    # -- set/time/mutex/bool writes (reference field.go:886-968) -----------

    def set_bit(self, row: int, col: int, timestamp: datetime | None = None) -> bool:
        o = self.options
        if self.is_bsi():
            raise ValueError(f"field {self.name} is an int field; use set_value")
        changed = False
        if not o.no_standard_view:
            std = self.create_view_if_not_exists(VIEW_STANDARD)
            if self.field_type in (FIELD_TYPE_MUTEX, FIELD_TYPE_BOOL):
                # bool fields are a 2-row mutex (reference view.go:273-276
                # boolVector, fragment.go:3122-3145)
                changed |= std.set_mutex(row, col)
            else:
                changed |= std.set_bit(row, col)
        if timestamp is not None:
            if not o.time_quantum:
                raise ValueError(
                    f"cannot set timestamp on non-time field {self.name}"
                )
            for vname in timequantum.views_by_time(
                VIEW_STANDARD, timestamp, o.time_quantum
            ):
                changed |= self.create_view_if_not_exists(vname).set_bit(row, col)
        if changed:
            self.stats.count("set_bit")
        return changed

    def clear_bit(self, row: int, col: int) -> bool:
        """Clears from standard and all time views (reference
        field.go:926-968 ClearBit w/ quantum-skip)."""
        changed = False
        for v in list(self.views.values()):
            if v.name == VIEW_STANDARD or v.name.startswith(VIEW_STANDARD + "_"):
                changed |= v.clear_bit(row, col)
        if changed:
            self.stats.count("clear_bit")
        return changed

    def get_bit(self, row: int, col: int) -> bool:
        v = self.view(VIEW_STANDARD)
        return v.get_bit(row, col) if v is not None else False

    # -- BSI reads/writes (reference field.go:1012-1160) --------------------

    def _check_bsi(self):
        if not self.is_bsi():
            raise ValueError(f"field {self.name} is not an int field")

    def grow_bit_depth(self, required: int) -> None:
        """Bit depth auto-grows to fit new values (reference
        field.go:1050-1067)."""
        if required > self.bit_depth:
            self.bit_depth = required

    def value_range(self) -> tuple[int, int]:
        """Min/max representable at current depth (reference
        field.go:1578-1586 bitDepthMin/Max)."""
        span = (1 << self.bit_depth) - 1
        return self.base - span, self.base + span

    def set_value(self, col: int, value: int) -> bool:
        self._check_bsi()
        o = self.options
        if value < o.min or value > o.max:
            raise ValueError(
                f"value {value} out of field range [{o.min}, {o.max}]"
            )
        stored = value - self.base
        self.grow_bit_depth(bit_depth_of(stored))
        view = self.create_view_if_not_exists(self.bsi_view_name())
        changed = view.set_value(col, self.bit_depth, stored)
        if changed:
            self.stats.count("set_value")
        return changed

    def value(self, col: int) -> tuple[int, bool]:
        self._check_bsi()
        view = self.view(self.bsi_view_name())
        if view is None:
            return 0, False
        stored, ok = view.value(col, self.bit_depth)
        return (stored + self.base, ok) if ok else (0, False)

    def clear_value(self, col: int) -> bool:
        self._check_bsi()
        view = self.view(self.bsi_view_name())
        return view.clear_value(col) if view is not None else False

    # -- bulk imports (reference field.go:1163-1352) ------------------------

    def import_bits(
        self,
        rows: Iterable[int],
        cols: Iterable[int],
        timestamps: Iterable[datetime | None] | None = None,
        clear: bool = False,
        pipeline=None,
        segments=None,
    ) -> None:
        """Routes (row, col[, ts]) triples to per-shard fragments.

        With a ``pipeline`` (ingest.IngestPipeline), the per-shard
        merges become sharded drains: every fragment's segment is
        submitted to the bounded import pool before any is awaited, so
        distinct shards merge on different workers, queued same-fragment
        segments coalesce into one merged apply, and each applied
        fragment's device upload overlaps the next segment's merge.
        ``segments`` optionally carries the batch pre-split by shard
        (``[(shard, rows, offs), ...]`` — the binary wire decoder
        already has it), skipping the sort/mask split here."""
        if clear and timestamps is not None:
            # reference field.go:1180
            raise ValueError("import clear is not supported with timestamps")
        rows = np.asarray(list(rows) if not isinstance(rows, np.ndarray) else rows, dtype=np.uint64)
        cols = np.asarray(list(cols) if not isinstance(cols, np.ndarray) else cols, dtype=np.uint64)
        self.stats.count("import_bits", len(cols))
        # import span (reference fragment.go:2245-2277)
        span = tracing.start_span("field.Import")
        span.set_tag("index", self.index).set_tag("field", self.name)
        span.set_tag("bits", int(len(cols)))
        with span:
            width = self.n_words * 32
            std = None if self.options.no_standard_view else self.create_view_if_not_exists(VIEW_STANDARD)
            mutexlike = (
                self.field_type in (FIELD_TYPE_MUTEX, FIELD_TYPE_BOOL)
                and not clear
            )
            if segments is None or std is None or mutexlike:
                shards = cols // width
                offs = cols % width
                segments = None
            handles = []
            for shard, seg_rows, seg_offs in (
                segments
                if segments is not None
                else (
                    (int(s), rows[shards == s], offs[shards == s])
                    for s in np.unique(shards)
                )
            ):
                if std is None:
                    continue
                frag = std.create_fragment_if_not_exists(int(shard))
                if mutexlike:
                    for r, c in zip(seg_rows, seg_offs):
                        frag.set_mutex(int(r), int(c))
                elif pipeline is not None:
                    handles.append(
                        self._submit_segment(
                            pipeline, frag, seg_rows,
                            seg_offs.astype(np.int64), clear,
                        )
                    )
                else:
                    frag.import_bits(
                        seg_rows, seg_offs.astype(np.int64), clear=clear
                    )
            if handles:
                pipeline.drain(handles)
            if timestamps is not None:
                ts_arr = list(timestamps)
                for i, ts in enumerate(ts_arr):
                    if ts is None:
                        continue
                    for vname in timequantum.views_by_time(
                        VIEW_STANDARD, ts, self.options.time_quantum
                    ):
                        self.create_view_if_not_exists(vname).set_bit(
                            int(rows[i]), int(cols[i])
                        )

    def _submit_segment(self, pipeline, frag, seg_rows, seg_cols, clear):
        """One shard's merge as a pipeline segment: same-fragment
        segments coalesce by key into ONE pool job (per-payload merges
        inside it — summed "changed" matches a concat-then-merge, and
        each merge sorts a modest batch), and the applied fragment is
        handed to the device-upload stage once per group."""

        def apply_group(payloads, _frag=frag):
            changed = 0
            for r, c in payloads:
                changed += _frag.import_bits(r, c, clear=clear)
            return changed, _frag

        return pipeline.submit_segment(
            (id(frag), bool(clear)), (seg_rows, seg_cols), apply_group
        )

    def import_values(self, cols: Iterable[int], values: Iterable[int], clear: bool = False, pipeline=None) -> None:
        self._check_bsi()
        cols = np.asarray(list(cols) if not isinstance(cols, np.ndarray) else cols, dtype=np.uint64)
        values = np.asarray(list(values) if not isinstance(values, np.ndarray) else values, dtype=np.int64)
        if len(values):
            stored = values - self.base
            self.grow_bit_depth(
                max(bit_depth_of(int(stored.min())), bit_depth_of(int(stored.max())))
            )
        view = self.create_view_if_not_exists(self.bsi_view_name())
        width = self.n_words * 32
        shards = cols // width
        offs = cols % width
        handles = []
        for shard in np.unique(shards):
            m = shards == shard
            frag = view.create_fragment_if_not_exists(int(shard))
            if pipeline is not None:
                # BSI merges keep a unique key (no coalescing: duplicate
                # columns across batches carry last-write-wins semantics
                # that a concatenated group would reorder), but still
                # drain shard-parallel with overlapped device uploads.
                def apply_group(payloads, _frag=frag):
                    [(c, v)] = payloads
                    return (
                        _frag.import_values(
                            c, v, self.bit_depth, clear=clear
                        ),
                        _frag,
                    )

                handles.append(
                    pipeline.submit_segment(
                        object(),
                        (offs[m].astype(np.int64), values[m] - self.base),
                        apply_group,
                    )
                )
            else:
                frag.import_values(
                    offs[m].astype(np.int64),
                    (values[m] - self.base),
                    self.bit_depth,
                    clear=clear,
                )
        if handles:
            pipeline.drain(handles)

    # -- schema -------------------------------------------------------------

    def to_dict(self) -> dict:
        return {"name": self.name, "options": self.options.to_dict()}
