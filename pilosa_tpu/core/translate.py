"""String-key translation: key <-> uint64 id (reference: translate.go).

The reference's ``TranslateStore`` is an mmap'd append-only log with
in-memory hash indexes and primary/replica streaming (translate.go:55-66,
91-97). Here the same interface with an in-memory implementation; the
storage layer adds the append-only-log-backed store, and the cluster layer
adds primary/replica semantics (non-primary stores are read-only and raise
on new-key writes, reference translate.go:52 ErrTranslateStoreReadOnly).

Ids are allocated sequentially from 1 (0 is never a valid translated id).
Columns translate per index; rows per (index, field).
"""

from __future__ import annotations

import threading
import time

from pilosa_tpu.obs import stats as stats_mod

# Process-global key-translation telemetry (the kernels.kernel_stats
# pattern): visible in /metrics and /debug/vars even when the holder
# runs a NopStatsClient.  Counters: translate_keys_created /
# translate_keys_found / translate_ids_looked_up / translate_log_appends
# (the last fed by storage/translatelog.py); histogram:
# translate_lookup_seconds per translate_keys batch.
translate_stats = stats_mod.MemStatsClient()


def telemetry_snapshot() -> dict:
    """Key-translation block for /debug/vars."""
    snap = translate_stats.snapshot()
    counters = snap["counters"]
    hist = snap["histograms"].get("translate_lookup_seconds")
    return {
        "keysCreated": counters.get("translate_keys_created", 0),
        "keysFound": counters.get("translate_keys_found", 0),
        "idsLookedUp": counters.get("translate_ids_looked_up", 0),
        "logAppends": counters.get("translate_log_appends", 0),
        "lookup": hist,
    }


class TranslateStoreReadOnlyError(Exception):
    pass


class TranslateStore:
    """In-memory bidirectional key map (reference inmem/translator.go:37)."""

    def __init__(self, read_only: bool = False):
        self._lock = threading.RLock()
        self.read_only = read_only
        # (index, field) -> key -> id; field "" means column keys.
        self._ids: dict[tuple[str, str], dict[str, int]] = {}
        self._keys: dict[tuple[str, str], list[str]] = {}
        # Called under the lock for every new (key, id) mapping — the
        # storage layer appends these to the on-disk log (reference
        # translate.go:37-40 InsertColumn/InsertRow entries).
        self.on_insert = None  # fn(index, field, key, id)
        # Ordered in-memory entry log: every new mapping, in apply
        # order.  Replicas stream it by offset (the role of the
        # reference's log-position replication, translate.go:91-97);
        # disk replay rebuilds it in original append order.
        self.log: list[tuple[str, str, str, int]] = []

    def _space(self, index: str, field: str):
        ids = self._ids.setdefault((index, field), {})
        keys = self._keys.setdefault((index, field), [])
        return ids, keys

    def translate_keys(self, index: str, field: str, keys: list[str], create: bool = True) -> list[int]:
        """keys -> ids, allocating new ids as needed (reference
        translate.go TranslateColumnsToUint64 / TranslateRowsToUint64)."""
        t0 = time.perf_counter()
        created = 0
        with self._lock:
            ids, key_list = self._space(index, field)
            out = []
            for k in keys:
                id_ = ids.get(k)
                if id_ is None:
                    if not create:
                        out.append(0)
                        continue
                    if self.read_only:
                        raise TranslateStoreReadOnlyError(
                            "translate store is read-only (replica)"
                        )
                    id_ = len(key_list) + 1
                    ids[k] = id_
                    key_list.append(k)
                    created += 1
                    self.log.append((index, field, k, id_))
                    if self.on_insert is not None:
                        self.on_insert(index, field, k, id_)
                out.append(id_)
        # telemetry outside the store lock: a scrape mid-batch must not
        # serialize against key allocation
        if created:
            translate_stats.count("translate_keys_created", created)
        found = len(keys) - created
        if found:
            translate_stats.count("translate_keys_found", found)
        translate_stats.timing("translate_lookup", time.perf_counter() - t0)
        return out

    def translate_ids(self, index: str, field: str, id_list: list[int]) -> list[str]:
        """ids -> keys; unknown ids map to "" (reference
        TranslateColumnToString)."""
        with self._lock:
            _, key_list = self._space(index, field)
            out = [
                key_list[i - 1] if 1 <= i <= len(key_list) else "" for i in id_list
            ]
        if id_list:
            translate_stats.count("translate_ids_looked_up", len(id_list))
        return out

    def translate_key(self, index: str, field: str, key: str, create: bool = True) -> int:
        return self.translate_keys(index, field, [key], create=create)[0]

    def translate_id(self, index: str, field: str, id_: int) -> str:
        return self.translate_ids(index, field, [id_])[0]

    def set_mapping(self, index: str, field: str, keys: list[str], id_list: list[int]) -> None:
        """Install key->id pairs allocated elsewhere (replica-side cache of
        the primary's log, reference translate.go replication :91-97).
        Bypasses read_only — this IS the replication write path."""
        with self._lock:
            ids, key_list = self._space(index, field)
            for k, i in zip(keys, id_list):
                if i <= 0 or k == "":
                    continue
                while len(key_list) < i:
                    key_list.append("")
                changed = key_list[i - 1] != k
                key_list[i - 1] = k
                ids[k] = i
                if changed:
                    self.log.append((index, field, k, i))
                    if self.on_insert is not None:
                        self.on_insert(index, field, k, i)

    def log_entries(
        self, offset: int, limit: int = 50_000
    ) -> tuple[list[tuple[str, str, str, int]], int]:
        """(entries since ``offset``, new offset) — the replication feed
        a replica pulls to mirror this store (reference translate.go
        :91-97 log streaming).  Bounded by ``limit`` per pull so one
        request never ships an unbounded log."""
        with self._lock:
            chunk = self.log[offset : offset + limit]
            return chunk, offset + len(chunk)

    def log_len(self) -> int:
        with self._lock:
            return len(self.log)

    # -- persistence --------------------------------------------------------

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "|".join(k): list(v) for k, v in self._keys.items()
            }

    def load_dict(self, d: dict) -> None:
        with self._lock:
            self._ids.clear()
            self._keys.clear()
            self.log = []
            for joined, key_list in d.items():
                index, _, field = joined.partition("|")
                self._keys[(index, field)] = list(key_list)
                self._ids[(index, field)] = {
                    k: i + 1 for i, k in enumerate(key_list)
                }
                # synthetic (id-ordered per space) log: a snapshot has no
                # append order, but the feed must still be complete
                self.log.extend(
                    (index, field, k, i + 1)
                    for i, k in enumerate(key_list)
                    if k
                )
