"""Fragment residency tracker: the working-set manager over DeviceBudget.

The budget (membudget.py) decides *which bytes stay*; this module decides
*which bytes should be hot* and *which should already be on their way*.
Together they turn the flat device/not-device split into explicit tiers:

    host-only --> staging --> device --> pinned
       ^             |           |          |
       +---- evict --+-----------+-- cool --+

* **host-only** — only the authoritative numpy mirror exists; the next
  query pays a cold H2D upload.
* **staging** — a predictive prefetch has been queued on the ingest
  ``DeviceUploader`` (the flight's shard set is known at window close,
  server/batcher.py) so the upload overlaps the previous flight's
  compute instead of stalling the dispatch.
* **device** — HBM-resident under clock/LRU eviction.
* **pinned** — hot enough (decayed hit rate over ``heat_half_life``)
  that the budget exempts it from eviction; cooling below the unpin
  threshold demotes it back to plain device residency.

The tracker itself is a thin process-global counter/policy object:
per-fragment state (heat, staging/prefetched flags, pin mirror) lives on
the fragment, updated under the fragment's own lock from
``Fragment.device_bits`` — the tracker never takes a fragment lock, so
the lock order stays fragment -> tracker/budget and never inverts.

Prefetch accounting: ``prefetch_issued`` counts fragments actually
queued on the uploader; an upload that still found work to ship marks
the fragment, and the first *query* hit on that copy counts
``prefetch_useful`` — the ratio is the lane-level proof that predictive
staging pays (BENCH residency lane bar: useful/issued >= 0.5).
"""

from __future__ import annotations

import threading
import time

from pilosa_tpu.core import membudget

STATE_HOST = "host"
STATE_STAGING = "staging"
STATE_DEVICE = "device"
STATE_PINNED = "pinned"

# Decayed-hits threshold above which a fragment's device copy is pinned,
# and the cooler threshold below which a pinned one is released.
DEFAULT_PIN_HEAT = 8.0
DEFAULT_UNPIN_HEAT = 2.0
DEFAULT_HEAT_HALF_LIFE = 10.0  # seconds


class ResidencyTracker:
    """Process-global residency policy + counters (obs: /metrics
    ``pilosa_device_*``, /debug/vars ``residency`` block)."""

    def __init__(
        self,
        pin_heat: float = DEFAULT_PIN_HEAT,
        unpin_heat: float = DEFAULT_UNPIN_HEAT,
        heat_half_life: float = DEFAULT_HEAT_HALF_LIFE,
    ):
        self.pin_heat = float(pin_heat)
        self.unpin_heat = float(unpin_heat)
        self.heat_half_life = max(0.001, float(heat_half_life))
        self._lock = threading.Lock()
        # query-path residency outcomes (prefetch traffic excluded)
        self.device_hits = 0
        self.device_misses = 0
        # predictive prefetch lifecycle
        self.prefetch_issued = 0
        self.prefetch_useful = 0
        self.prefetch_uploads = 0
        self.prefetch_wasted = 0  # upload found the copy already resident
        self.prefetch_dropped = 0  # uploader busy with ingest; not queued
        self.prefetch_errors = 0
        self.prefetch_h2d_bytes = 0
        # pin policy outcomes
        self.auto_pins = 0
        self.auto_unpins = 0
        self.stack_hits = 0
        self.stack_pins = 0
        # threads syncing on behalf of the prefetcher mark themselves so
        # their device_bits calls don't pollute query hit/miss rates
        self._tls = threading.local()

    # -- prefetch-thread marker ---------------------------------------------

    def in_prefetch(self) -> bool:
        return getattr(self._tls, "prefetching", False)

    def enter_prefetch(self) -> None:
        self._tls.prefetching = True

    def exit_prefetch(self) -> None:
        self._tls.prefetching = False

    # -- heat ----------------------------------------------------------------

    def _decayed_heat(self, frag, now: float) -> float:
        dt = now - frag._heat_t
        if dt <= 0:
            return frag._heat
        return frag._heat * (0.5 ** (dt / self.heat_half_life))

    def heat_of(self, frag) -> float:
        """Current decayed heat (read-only; safe without the fragment
        lock — a torn read only skews a diagnostic)."""
        return self._decayed_heat(frag, time.monotonic())

    def state_of(self, frag) -> str:
        """Residency tier for /debug/fragments (racy read by design —
        introspection must not take query-path locks)."""
        if frag._device is not None:
            return STATE_PINNED if frag._res_pinned else STATE_DEVICE
        if frag._res_staging:
            return STATE_STAGING
        return STATE_HOST

    # -- unified residency outcomes (fragments AND field stacks: both
    #    are budget-accounted device assets) ---------------------------------

    def note_hit(self, prefetched: bool = False) -> None:
        """A query found its device asset already resident; when a
        prefetch paid that asset's upload, it proved useful."""
        with self._lock:
            self.device_hits += 1
            if prefetched:
                self.prefetch_useful += 1

    def note_miss(self) -> None:
        """A query paid a cold upload/build on its own path."""
        with self._lock:
            self.device_misses += 1

    def note_prefetch_upload(self, h2d_bytes: int) -> None:
        """The prefetch thread actually shipped bytes for an asset."""
        with self._lock:
            self.prefetch_uploads += 1
            self.prefetch_h2d_bytes += int(h2d_bytes)

    def note_prefetch_wasted(self) -> None:
        """The prefetch thread found the asset already resident (the
        query beat it there, or the submit was stale)."""
        with self._lock:
            self.prefetch_wasted += 1

    # -- fragment-path hook (called from Fragment.device_bits, under the
    #    fragment's lock; tracker/budget locks nest inside) ------------------

    def note_sync(self, frag, was_resident: bool, h2d_bytes: int) -> None:
        if self.in_prefetch():
            # the uploader's own sync: prefetch bookkeeping, not a query
            frag._res_staging = False
            if was_resident and not h2d_bytes:
                self.note_prefetch_wasted()
            else:
                frag._res_prefetched = True
                self.note_prefetch_upload(h2d_bytes)
            return
        frag._res_staging = False
        prefetched = frag._res_prefetched
        frag._res_prefetched = False
        if was_resident:
            self.note_hit(prefetched)
        else:
            self.note_miss()
        now = time.monotonic()
        heat = self._decayed_heat(frag, now) + 1.0
        frag._heat = heat
        frag._heat_t = now
        self._repin(frag, heat)

    def _repin(self, frag, heat: float) -> None:
        """Promote/demote the fragment's pin to match its heat."""
        budget = membudget.default_budget()
        key = frag._budget_key
        if key is None:
            return
        if not frag._res_pinned and heat >= self.pin_heat:
            if budget.pin(key):
                frag._res_pinned = True
                with self._lock:
                    self.auto_pins += 1
        elif frag._res_pinned and heat < self.unpin_heat:
            budget.unpin(key)
            frag._res_pinned = False
            with self._lock:
                self.auto_unpins += 1

    def note_dropped(self, frag) -> None:
        """The device copy is gone (explicit drop or budget eviction):
        clear the tier flags so state_of can't report a phantom pin."""
        frag._res_pinned = False
        frag._res_prefetched = False
        frag._res_staging = False

    # -- stack-cache policy hooks (exec/executor.py) -------------------------

    def note_stack_hit(self) -> None:
        with self._lock:
            self.stack_hits += 1

    def maybe_pin_stack(self, budget, bkey, hits: int) -> bool:
        """Pin a field stack once its hit count clears the heat bar —
        the executor's cache entries feed the same pin policy as
        fragments (use stamps, not insertion order)."""
        if hits < self.pin_heat:
            return False
        if budget.pin(bkey):
            with self._lock:
                self.stack_pins += 1
            return True
        return False

    # -- prefetch issue-side accounting --------------------------------------

    def note_prefetch_issued(self, n: int = 1) -> None:
        with self._lock:
            self.prefetch_issued += n

    def note_prefetch_dropped(self, n: int = 1) -> None:
        with self._lock:
            self.prefetch_dropped += n

    def note_prefetch_error(self) -> None:
        with self._lock:
            self.prefetch_errors += 1

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            issued = self.prefetch_issued
            useful = self.prefetch_useful
            return {
                "deviceHits": self.device_hits,
                "deviceMisses": self.device_misses,
                "hitRate": round(
                    self.device_hits
                    / max(1, self.device_hits + self.device_misses),
                    4,
                ),
                "prefetchIssued": issued,
                "prefetchUseful": useful,
                "prefetchUsefulFrac": round(useful / max(1, issued), 4),
                "prefetchUploads": self.prefetch_uploads,
                "prefetchWasted": self.prefetch_wasted,
                "prefetchDropped": self.prefetch_dropped,
                "prefetchErrors": self.prefetch_errors,
                "prefetchH2dBytes": self.prefetch_h2d_bytes,
                "autoPins": self.auto_pins,
                "autoUnpins": self.auto_unpins,
                "stackHits": self.stack_hits,
                "stackPins": self.stack_pins,
                "pinHeat": self.pin_heat,
                "unpinHeat": self.unpin_heat,
                "heatHalfLife": self.heat_half_life,
            }


_default: ResidencyTracker | None = None
_default_lock = threading.Lock()


def default_tracker() -> ResidencyTracker:
    global _default
    with _default_lock:
        if _default is None:
            _default = ResidencyTracker()
        return _default


def configure(**kwargs) -> ResidencyTracker:
    """Install a fresh process-wide tracker (tests / embedders)."""
    global _default
    with _default_lock:
        _default = ResidencyTracker(**kwargs)
        return _default
