"""Row/column attribute stores (reference: attr.go, boltdb/attrstore.go).

Arbitrary key/value metadata attached to row ids (per field) and column ids
(per index). The reference backs this with BoltDB + an LRU cache; here a
thread-safe dict with 100-id blocks + checksums for the anti-entropy diff
protocol (reference attr.go:81-120 AttrBlock/attrBlocks.Diff). Persistence
is JSON via the storage layer — attrs are never on the device data path.
"""

from __future__ import annotations

import hashlib
import json
import threading
from typing import Any

# reference attr.go:29 attrBlockSize.
ATTR_BLOCK_SIZE = 100


class AttrStore:
    def __init__(self):
        self._lock = threading.RLock()
        self._attrs: dict[int, dict[str, Any]] = {}

    def attrs(self, id_: int) -> dict[str, Any]:
        with self._lock:
            return dict(self._attrs.get(id_, {}))

    def set_attrs(self, id_: int, attrs: dict[str, Any]) -> None:
        """Merge semantics: None deletes a key (reference attr.go
        SetAttrs)."""
        with self._lock:
            cur = self._attrs.setdefault(id_, {})
            for k, v in attrs.items():
                if v is None:
                    cur.pop(k, None)
                else:
                    cur[k] = v
            if not cur:
                del self._attrs[id_]

    def set_bulk_attrs(self, attrs_by_id: dict[int, dict[str, Any]]) -> None:
        with self._lock:
            for id_, attrs in attrs_by_id.items():
                self.set_attrs(id_, attrs)

    def ids(self) -> list[int]:
        with self._lock:
            return sorted(self._attrs)

    # -- anti-entropy blocks (reference attr.go:81-120) ---------------------

    def blocks(self) -> list[tuple[int, bytes]]:
        """(block_id, checksum) pairs over 100-id blocks."""
        with self._lock:
            by_block: dict[int, list[int]] = {}
            for id_ in self._attrs:
                by_block.setdefault(id_ // ATTR_BLOCK_SIZE, []).append(id_)
            out = []
            for block_id in sorted(by_block):
                h = hashlib.blake2b(digest_size=16)
                for id_ in sorted(by_block[block_id]):
                    h.update(
                        json.dumps(
                            [id_, self._attrs[id_]], sort_keys=True
                        ).encode()
                    )
                out.append((block_id, h.digest()))
            return out

    def block_data(self, block_id: int) -> dict[int, dict[str, Any]]:
        with self._lock:
            lo = block_id * ATTR_BLOCK_SIZE
            hi = lo + ATTR_BLOCK_SIZE
            return {
                id_: dict(a) for id_, a in self._attrs.items() if lo <= id_ < hi
            }

    # -- persistence --------------------------------------------------------

    def to_dict(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            return {str(k): dict(v) for k, v in self._attrs.items()}

    def load_dict(self, d: dict[str, dict[str, Any]]) -> None:
        with self._lock:
            self._attrs = {int(k): dict(v) for k, v in d.items()}
