"""Row/column attribute stores (reference: attr.go, boltdb/attrstore.go).

Arbitrary key/value metadata attached to row ids (per field) and column
ids (per index).  The reference backs this with BoltDB plus an LRU read
cache (boltdb/attrstore.go:37-90); here the store is organized as 100-id
BLOCKS end to end:

* blocks are the persistence unit — the storage layer writes only the
  blocks dirtied since the last flush (no whole-store JSON rewrite),
* blocks are the caching unit — with a ``backend`` attached, blocks load
  lazily on first touch and CLEAN blocks are evicted LRU past
  ``cache_blocks``, so a huge store doesn't live in memory,
* blocks are the anti-entropy unit — 100-id checksums diff against
  replicas (reference attr.go:81-120 AttrBlock/attrBlocks.Diff).

Attrs are never on the device data path.
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import OrderedDict
from typing import Any

# reference attr.go:29 attrBlockSize.
ATTR_BLOCK_SIZE = 100


class AttrStore:
    # loaded-block LRU cap when a backend is attached (clean blocks
    # only; dirty blocks are pinned until drained).  4096 blocks x 100
    # ids bounds resident attrs at ~400k ids.
    DEFAULT_CACHE_BLOCKS = 4096

    def __init__(self, backend=None, cache_blocks: int = DEFAULT_CACHE_BLOCKS):
        self._lock = threading.RLock()
        # block id -> {id -> attrs}; OrderedDict in LRU order
        self._blocks: OrderedDict[int, dict[int, dict[str, Any]]] = (
            OrderedDict()
        )
        self._dirty: set[int] = set()
        self.backend = backend  # .load_block(bid) -> dict|None, .block_ids()
        self.cache_blocks = cache_blocks

    # -- block plumbing -----------------------------------------------------

    def _block(self, bid: int) -> dict[int, dict[str, Any]]:
        """The block's id->attrs dict, loading through the backend on
        first touch (caller holds the lock)."""
        blk = self._blocks.get(bid)
        if blk is not None:
            self._blocks.move_to_end(bid)
            return blk
        blk = {}
        if self.backend is not None:
            loaded = self.backend.load_block(bid)
            if loaded:
                blk = {int(k): dict(v) for k, v in loaded.items()}
        self._blocks[bid] = blk
        self._evict(protect=bid)
        return blk

    def _evict(self, protect: int | None = None) -> None:
        """Drop least-recently-used CLEAN blocks past the cap (only
        meaningful with a backend — without one every block is its sole
        copy and is never evicted).  ``protect`` pins the block being
        handed to the CURRENT caller: it may be about to dirty it
        (set_attrs marks dirty only after _block returns), and evicting
        it here would orphan that mutation."""
        if self.backend is None:
            return
        while len(self._blocks) > self.cache_blocks:
            victim = next(
                (
                    b
                    for b in self._blocks
                    if b not in self._dirty and b != protect
                ),
                None,
            )
            if victim is None:
                return  # everything dirty/pinned: over-cap until drain
            del self._blocks[victim]

    def _all_block_ids(self) -> list[int]:
        ids = set(self._blocks)
        if self.backend is not None:
            ids.update(self.backend.block_ids())
        return sorted(ids)

    # -- reads / writes -----------------------------------------------------

    def attrs(self, id_: int) -> dict[str, Any]:
        with self._lock:
            return dict(self._block(id_ // ATTR_BLOCK_SIZE).get(id_, {}))

    def set_attrs(self, id_: int, attrs: dict[str, Any]) -> None:
        """Merge semantics: None deletes a key (reference attr.go
        SetAttrs)."""
        with self._lock:
            bid = id_ // ATTR_BLOCK_SIZE
            blk = self._block(bid)
            cur = blk.setdefault(id_, {})
            for k, v in attrs.items():
                if v is None:
                    cur.pop(k, None)
                else:
                    cur[k] = v
            if not cur:
                del blk[id_]
            self._dirty.add(bid)

    def set_bulk_attrs(self, attrs_by_id: dict[int, dict[str, Any]]) -> None:
        with self._lock:
            for id_, attrs in attrs_by_id.items():
                self.set_attrs(id_, attrs)

    def ids(self) -> list[int]:
        with self._lock:
            out: list[int] = []
            for bid in self._all_block_ids():
                out.extend(self._block(bid))
            return sorted(out)

    # -- anti-entropy blocks (reference attr.go:81-120) ---------------------

    def blocks(self) -> list[tuple[int, bytes]]:
        """(block_id, checksum) pairs over 100-id blocks."""
        with self._lock:
            out = []
            for bid in self._all_block_ids():
                blk = self._block(bid)
                if not blk:
                    continue
                h = hashlib.blake2b(digest_size=16)
                for id_ in sorted(blk):
                    h.update(
                        json.dumps([id_, blk[id_]], sort_keys=True).encode()
                    )
                out.append((bid, h.digest()))
            return out

    def block_data(self, block_id: int) -> dict[int, dict[str, Any]]:
        with self._lock:
            return {
                id_: dict(a) for id_, a in self._block(block_id).items()
            }

    # -- persistence --------------------------------------------------------

    def flush_dirty(self) -> None:
        """Persist every block dirtied since the last flush through
        ``self.backend.write_blocks({block_id: block_data})`` — the
        storage layer writes exactly these files (the reference's
        per-bucket BoltDB writes play the same role,
        boltdb/attrstore.go:37-90).

        The dirty set is cleared (and drained blocks become evictable)
        only AFTER the writer returns: a failed write (disk full) leaves
        every block dirty for the next flush instead of silently
        dropping it.  The lock is held across the write so a concurrent
        ``attrs()`` read cannot load-and-cache the stale on-disk block
        mid-flush and keep serving it after the flush lands — attr
        flushes are small (dirty blocks only) and attrs are never on
        the query hot path, so blocking reads for the write is the
        right trade."""
        with self._lock:
            if self.backend is None or not self._dirty:
                return
            self.backend.write_blocks(
                {bid: self.block_data(bid) for bid in self._dirty}
            )
            self._dirty.clear()
            self._evict()

    def to_dict(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            out: dict[str, dict[str, Any]] = {}
            for bid in self._all_block_ids():
                for id_, a in self._block(bid).items():
                    out[str(id_)] = dict(a)
            return out

    def load_dict(self, d: dict[str, dict[str, Any]]) -> None:
        """Install a whole-store snapshot (legacy persistence format and
        the wire path); marks everything dirty so the next flush lands
        it block-wise."""
        with self._lock:
            self._blocks.clear()
            self._dirty.clear()
            for k, v in d.items():
                id_ = int(k)
                bid = id_ // ATTR_BLOCK_SIZE
                self._blocks.setdefault(bid, {})[id_] = dict(v)
                self._dirty.add(bid)
