"""View: a named sub-bitmap of a field (reference: view.go).

View names: ``"standard"`` for the main bitmap, ``standard_YYYYMMDDHH``
prefixes for time views, ``bsig_<field>`` for the BSI view of an int field
(reference view.go:33-38). A view owns one fragment per shard
(reference view.go:41 ``fragments`` map)."""

from __future__ import annotations

import threading

from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.shardwidth import SHARD_WORDS

VIEW_STANDARD = "standard"
VIEW_BSI_PREFIX = "bsig_"


def view_name_bsi(field_name: str) -> str:
    return VIEW_BSI_PREFIX + field_name


class View:
    def __init__(self, index: str, field: str, name: str, n_words: int = SHARD_WORDS):
        self.index = index
        self.field = field
        self.name = name
        self.n_words = n_words
        self._lock = threading.RLock()
        self.fragments: dict[int, Fragment] = {}
        # Hook invoked when a new fragment (shard) appears, used by the
        # cluster layer to broadcast CreateShardMessage
        # (reference view.go:239-261).
        self.on_create_fragment = None

    def fragment(self, shard: int) -> Fragment | None:
        return self.fragments.get(shard)

    def create_fragment_if_not_exists(self, shard: int) -> Fragment:
        """reference view.go:223 CreateFragmentIfNotExists."""
        with self._lock:
            frag = self.fragments.get(shard)
            if frag is None:
                frag = Fragment(self.index, self.field, self.name, shard, self.n_words)
                self.fragments[shard] = frag
                if self.on_create_fragment is not None:
                    self.on_create_fragment(self, shard)
            return frag

    def available_shards(self) -> set[int]:
        return set(self.fragments)

    def drop_fragment(self, shard: int) -> bool:
        """Remove a fragment from memory (reference holder.go:898-926
        holderCleaner). Disk-backed callers must also detach the backing
        store via HolderStore.delete_fragment — file lifecycle belongs to
        the storage layer, not the data model."""
        with self._lock:
            return self.fragments.pop(shard, None) is not None

    # -- column-addressed ops (abs column -> shard + offset) ---------------

    def _split(self, col: int) -> tuple[int, int]:
        width = self.n_words * 32
        return col // width, col % width

    def set_bit(self, row: int, col: int) -> bool:
        shard, off = self._split(col)
        return self.create_fragment_if_not_exists(shard).set_bit(row, off)

    def clear_bit(self, row: int, col: int) -> bool:
        shard, off = self._split(col)
        frag = self.fragment(shard)
        return frag.clear_bit(row, off) if frag is not None else False

    def get_bit(self, row: int, col: int) -> bool:
        shard, off = self._split(col)
        frag = self.fragment(shard)
        return frag.get_bit(row, off) if frag is not None else False

    def set_mutex(self, row: int, col: int) -> bool:
        shard, off = self._split(col)
        return self.create_fragment_if_not_exists(shard).set_mutex(row, off)

    def set_value(self, col: int, bit_depth: int, value: int) -> bool:
        shard, off = self._split(col)
        return self.create_fragment_if_not_exists(shard).set_value(off, bit_depth, value)

    def value(self, col: int, bit_depth: int) -> tuple[int, bool]:
        shard, off = self._split(col)
        frag = self.fragment(shard)
        return frag.value(off, bit_depth) if frag is not None else (0, False)

    def clear_value(self, col: int) -> bool:
        shard, off = self._split(col)
        frag = self.fragment(shard)
        return frag.clear_value(off) if frag is not None else False
