"""Fragment: the (index, field, view, shard) storage unit.

The reference's fragment is one mmap'd roaring bitmap holding all rows of a
2^20-column shard concatenated at ``pos = row*ShardWidth + col%ShardWidth``
(reference fragment.go:100-159, 3077-3080). Here a fragment is a dense
bitmap tensor:

* **host mirror** ``uint32[capacity, W]`` (numpy) — the authoritative copy.
  Mutations (set/clear/import) are applied here first, giving exact
  changed-bit accounting (the reference gets this from roaring's
  ``Add/Remove`` return values) with zero device round-trips.
* **device copy** ``uint32[capacity+1, W]`` (jax, HBM) — the compute copy,
  synced lazily before queries: a handful of dirty rows go up as a scatter
  update, wholesale changes as a fresh ``device_put``. The extra final row
  is permanently zero so missing row-ids can gather it (avoids dynamic
  shapes under jit).

Row-ids are arbitrary uint64 (the reference allows e.g. hashed ids), so the
row axis is *sparse*: row-id -> slot via a host dict, with capacity grown in
powers of two so jitted kernels see a bounded set of shapes. The column
axis is dense — that asymmetry (sparse rows × dense 2^20-bit columns) is
the central data-layout decision for HBM residency: queries are
row-oriented, and a row is one 128 KiB word vector that XLA streams at HBM
bandwidth.

Write batching replaces the reference's op-log+snapshot cadence
(fragment.go:84 MaxOpN=10000): mutations accumulate in the host mirror and
flush to HBM in one batched update, amortizing transfer exactly the way the
reference amortizes fsyncs.
"""

from __future__ import annotations

import itertools
import threading
import weakref
from contextlib import contextmanager
from typing import Iterable

import numpy as np

import jax
import jax.numpy as jnp

from pilosa_tpu.core import membudget, residency
from pilosa_tpu.ops import _hostops, bitops, kernels
from pilosa_tpu.shardwidth import SHARD_WIDTH, SHARD_WORDS

# BSI row layout within a bsig_* view (reference fragment.go:90-96).
BSI_EXISTS_BIT = 0
BSI_SIGN_BIT = 1
BSI_OFFSET_BIT = 2

# Rows per anti-entropy checksum block (reference fragment.go:81).
HASH_BLOCK_SIZE = 100

_MIN_CAPACITY = 8

# Paranoia mode: invariant checks after every mutation (the analogue of
# the reference's `roaringparanoia` build tag, roaring/roaring_paranoia.go).
import os as _os

PARANOIA = bool(_os.environ.get("PILOSA_TPU_PARANOIA"))


class FragmentInvariantError(AssertionError):
    """Internal coherence violation between slot map, host mirror, and
    device copy (reference Container.check, roaring.go:2967-3028)."""


def _retry_evict(ref) -> None:
    """Complete a deferred HBM eviction from a lock-free thread: blocking
    acquire is safe here because this thread holds no fragment locks."""
    f = ref()
    if f is None:
        return
    with f._lock:
        if f._evict_pending:
            f._evict_pending = False
            f._device = None
            f._dirty.clear()
            f._delta_reset()
            # The flag may be stale: a concurrent device_bits can have
            # re-admitted the copy after the deferral was recorded.  The
            # accounting must follow the copy we just dropped, or the
            # budget over-counts those bytes forever (release is a no-op
            # when the budget already evicted the entry).
            if f._budget_key is not None:
                membudget.default_budget().release(f._budget_key)
            residency.default_tracker().note_dropped(f)


@jax.jit
def _scatter_rows(device_bits, slots, rows):
    return device_bits.at[slots].set(rows)


@jax.jit
def _scatter_words(device_bits, flat_idx, vals):
    """Word-granular device update: flat positions into the row-major
    [capacity+1, W] copy.  Ships 8 bytes per CHANGED WORD instead of a
    whole row per dirty slot — the winning path when a write batch
    touches many rows sparsely (the common ingest shape)."""
    shape = device_bits.shape
    return device_bits.reshape(-1).at[flat_idx].set(vals).reshape(shape)


class Fragment:
    """Dense bitmap tensor for one (index, field, view, shard)."""

    _epoch_counter = itertools.count()

    def __init__(
        self,
        index: str = "",
        field: str = "",
        view: str = "",
        shard: int = 0,
        n_words: int = SHARD_WORDS,
    ):
        self.index = index
        self.field = field
        self.view = view
        self.shard = shard
        self.n_words = n_words
        self.shard_width = n_words * 32

        self._lock = threading.RLock()
        self._slot_of: dict[int, int] = {}  # row id -> slot
        self._rowids: list[int] = []  # slot -> row id
        self._set_host(np.zeros((0, n_words), dtype=np.uint32))
        self._device: jax.Array | None = None
        self._dirty: set[int] = set()
        # word-granular change tracking riding alongside _dirty: flat
        # (slot * n_words + word) indices accumulated per mutation
        # batch; None = degraded (an untracked mutation happened or the
        # delta grew past worthwhile), meaning sync falls back to the
        # row/full paths.  Always cleared together with _dirty.
        # fields are established by _delta_reset below — ONE place owns
        # the reset semantics (including the int32-eligibility degrade)
        self._word_delta: list[np.ndarray] | None = None
        self._word_delta_small: set[int] = set()
        self._word_delta_n = 0
        self._word_delta_compact_at = 0
        self._counts: np.ndarray | None = None  # per-slot cached popcounts
        # (epoch, version)-keyed storage-shape stats (container_profile):
        # /debug/fragments and the flight planner's cost model read these
        # per request, so they must not rescan roaring containers while
        # the fragment is unchanged
        self._container_profile: tuple | None = None
        # Monotonic mutation counter: cheap cache key for stacked-tensor
        # caches built over this fragment (executor batch fast path).
        self.version = 0
        # Process-unique object nonce: a DIFFERENT Fragment later serving
        # the same shard (dropped by resize cleanup, re-created when the
        # shard moves back) must never alias a cached stack's version —
        # both fragments count versions from 0, so the number alone can
        # coincide. Cache keys pair (epoch, version).
        self.epoch = next(self._epoch_counter)
        # op accounting for the storage layer's snapshot trigger
        # (reference fragment.go:84 MaxOpN, 2284-2293).
        self.op_n = 0
        self.on_op = None  # callback(fragment) after mutations
        # optional storage.FragmentFile: mutations append to its op log
        # (reference fragment.go:453 storage.OpWriter). Lock order is
        # always fragment._lock (outer) -> store lock (inner).
        self.store = None
        # HBM accounting key for the device copy (syswrap analogue,
        # membudget); created lazily on first device sync.
        self._budget_key = None
        # set by the budget's evict callback when it could not take the
        # lock; honored at the next device sync
        self._evict_pending = False
        # bytes shipped host->device by the most recent device_bits()
        # sync (0 when the device copy was already current); the ingest
        # uploader reads this for its overlap accounting
        self.last_sync_h2d_bytes = 0
        # residency-tier state owned by core/residency.py's tracker:
        # decayed hit heat, predictive-prefetch flags, and a mirror of
        # the budget's pin bit (authoritative copy lives in membudget)
        self._heat = 0.0
        self._heat_t = 0.0
        self._res_staging = False  # queued on the prefetch uploader
        self._res_prefetched = False  # prefetch paid the upload; unqueried
        self._res_pinned = False
        self._delta_reset()

    def _set_host(self, arr: np.ndarray) -> None:
        """The ONLY way to (re)assign the host mirror: keeps the cached
        base address in lockstep (the latency tier builds 100+ row
        addresses per query off ``_host_addr``; __array_interface__
        costs ~1 us per access vs ~60 ns for the attribute — and a
        reassignment that forgot the pair would hand the native kernel
        a pointer into the freed old buffer)."""
        self._host = arr
        self._host_addr = arr.__array_interface__["data"][0]

    # -- row bookkeeping ----------------------------------------------------

    @property
    def capacity(self) -> int:
        return self._host.shape[0]

    def row_ids(self) -> list[int]:
        """Sorted ids of rows that physically exist (may include all-zero
        rows that were written then cleared — same as the reference, where
        cleared containers linger until snapshot)."""
        with self._lock:
            return sorted(self._slot_of)

    def has_row(self, row: int) -> bool:
        return row in self._slot_of

    def _grow(self, need: int) -> None:
        cap = max(_MIN_CAPACITY, self.capacity)
        while cap < need:
            cap *= 2
        if cap != self.capacity:
            grown = np.zeros((cap, self.n_words), dtype=np.uint32)
            grown[: self.capacity] = self._host
            self._set_host(grown)
            self._drop_device()  # full re-upload on next query

    def _slots_batch(self, row_ids: np.ndarray) -> np.ndarray:
        """Slots for every row id (ascending unique array), creating
        missing ones with ONE capacity grow — a per-row _slot loop
        re-copies the whole mirror at every doubling step during large
        imports (caller holds the lock)."""
        out = np.empty(row_ids.size, dtype=np.int64)
        missing = []
        for i, r in enumerate(row_ids):
            s = self._slot_of.get(int(r))
            if s is None:
                missing.append(i)
            else:
                out[i] = s
        if missing:
            self._grow(len(self._rowids) + len(missing))
            for i in missing:
                r = int(row_ids[i])
                s = len(self._rowids)
                self._slot_of[r] = s
                self._rowids.append(r)
                out[i] = s
            if self._counts is not None:
                self._counts = None
        return out

    def _slot(self, row: int, create: bool = False) -> int | None:
        s = self._slot_of.get(row)
        if s is None and create:
            s = len(self._rowids)
            self._grow(s + 1)
            self._slot_of[row] = s
            self._rowids.append(row)
            if self._counts is not None:
                self._counts = None
        return s

    def _drop_device(self) -> None:
        """Drop the device copy and its budget accounting (caller holds
        the lock); host mirror stays authoritative."""
        self._device = None
        self._dirty.clear()
        self._delta_reset()
        if self._budget_key is not None:
            membudget.default_budget().release(self._budget_key)
        residency.default_tracker().note_dropped(self)

    # -- mutation -----------------------------------------------------------

    def _touch(self, slot: int, tracked: bool = False) -> None:
        """Mark a slot mutated.  ``tracked=True`` promises the caller
        already recorded the exact changed words via _delta_note*; any
        untracked mutation degrades word-granular sync (correct by
        default for future mutation paths)."""
        if not tracked:
            self._delta_degrade()
        self._dirty.add(slot)
        self._counts = None
        self.version += 1
        self.op_n += 1
        if self.on_op is not None:
            self.on_op(self)
        if PARANOIA:
            self.check_invariants()

    # word-delta tracking degrades past this fraction of the fragment's
    # words — a full re-upload is cheaper than a giant scatter
    _WORD_DELTA_MAX_FRACTION = 8

    def _delta_over_budget(self) -> bool:
        """Whether the delta outgrew its budget.  Duplicate notes (the
        same words mutated repeatedly) inflate the raw count, so compact
        to unique positions before deciding to degrade — but only past
        2x budget (hysteresis): compacting at the boundary would re-sort
        the whole delta on every subsequent mutation."""
        budget = (
            max(1, self.capacity) * self.n_words
            // self._WORD_DELTA_MAX_FRACTION
        )
        raw = self._word_delta_n + len(self._word_delta_small)
        if raw <= budget:
            return False
        if self._word_delta_n == 0:
            return True  # the set alone is already unique: genuinely over
        if raw < self._word_delta_compact_at:
            return False  # tolerate duplicates until raw doubles again —
            # a delta parked at ~budget unique positions must not be
            # re-sorted on every subsequent duplicate note
        flat = self._delta_flat()
        self._word_delta = [flat]
        self._word_delta_small = set()
        self._word_delta_n = len(flat)
        self._word_delta_compact_at = 2 * max(len(flat), budget)
        return len(flat) > budget

    def _delta_note(self, flat: np.ndarray) -> None:
        """Record changed flat word positions (slot * n_words + word)
        for the word-granular device sync (caller holds the lock)."""
        if self._word_delta is None:
            return
        if (self.capacity + 1) * self.n_words >= 2**31:
            # the word path's int32 scatter can never serve this
            # fragment; don't accumulate notes it can't use
            self._delta_degrade()
            return
        self._word_delta.append(np.asarray(flat, dtype=np.int64))
        self._word_delta_n += len(flat)
        if self._delta_over_budget():
            self._delta_degrade()

    def _delta_note_word(self, slot: int, word: int) -> None:
        """Single-word note: a plain set add (no per-bit ndarray churn),
        naturally deduped so toggle-heavy workloads on few words don't
        inflate the degrade counter."""
        if self._word_delta is not None:
            self._word_delta_small.add(slot * self.n_words + word)
            if self._delta_over_budget():
                self._delta_degrade()

    def _delta_note_mask(self, slot: int, mask: np.ndarray) -> None:
        """Record every set word of ``mask`` as changed for ``slot``."""
        if self._word_delta is not None:
            w = np.flatnonzero(mask)
            self._delta_note(slot * self.n_words + w.astype(np.int64))

    def _delta_degrade(self) -> None:
        """An untracked or too-large mutation: word-granular sync is off
        until the next device rebuild."""
        self._word_delta = None
        self._word_delta_small = set()
        self._word_delta_n = 0

    def _delta_reset(self) -> None:
        if (self.capacity + 1) * self.n_words >= 2**31:
            # the int32 word scatter can never serve this fragment:
            # don't track notes it can't use (capacity only changes
            # through paths that re-run this reset)
            self._delta_degrade()
            return
        self._word_delta = []
        self._word_delta_small = set()
        self._word_delta_n = 0
        self._word_delta_compact_at = 0

    def _delta_flat(self) -> np.ndarray:
        """All noted word positions, deduped (caller checked not-None)."""
        parts = list(self._word_delta)
        if self._word_delta_small:
            parts.append(
                np.fromiter(
                    self._word_delta_small,
                    dtype=np.int64,
                    count=len(self._word_delta_small),
                )
            )
        if not parts:
            return np.empty(0, np.int64)
        return np.unique(np.concatenate(parts))

    def check_invariants(self, device: bool = False) -> None:
        """Verify slot-map ↔ host-mirror ↔ device-copy coherence; raises
        FragmentInvariantError on violation (reference `ctl check` +
        Container.check, ctl/check.go:47-133, roaring.go:2967-3028).
        ``device=True`` additionally pulls the device copy to host and
        compares every clean row — expensive, test-only."""
        with self._lock:
            if len(self._rowids) != len(self._slot_of):
                raise FragmentInvariantError(
                    f"rowids/slot_of size mismatch: "
                    f"{len(self._rowids)} != {len(self._slot_of)}"
                )
            for r, s in self._slot_of.items():
                if not (0 <= s < len(self._rowids)) or self._rowids[s] != r:
                    raise FragmentInvariantError(
                        f"slot map incoherent at row {r} -> slot {s}"
                    )
            if self._host.shape != (self.capacity, self.n_words):
                raise FragmentInvariantError(
                    f"host mirror shape {self._host.shape} != "
                    f"({self.capacity}, {self.n_words})"
                )
            if len(self._rowids) > self.capacity:
                raise FragmentInvariantError("more rows than capacity")
            if self._host.dtype != np.uint32:
                raise FragmentInvariantError(
                    f"host mirror dtype {self._host.dtype}"
                )
            if self._counts is not None:
                want = np.bitwise_count(
                    self._host[: len(self._rowids)]
                ).sum(axis=1)
                if not np.array_equal(
                    np.asarray(self._counts, dtype=np.int64),
                    want.astype(np.int64),
                ):
                    raise FragmentInvariantError("stale row-count cache")
            if device and self._device is not None:
                dev = np.asarray(self._device)
                if dev.shape != (self.capacity + 1, self.n_words):
                    raise FragmentInvariantError(
                        f"device copy shape {dev.shape}"
                    )
                if dev[self.capacity].any():
                    raise FragmentInvariantError("zero row is not zero")
                clean = [
                    s
                    for s in range(len(self._rowids))
                    if s not in self._dirty
                ]
                if clean and not np.array_equal(
                    dev[clean], self._host[clean]
                ):
                    raise FragmentInvariantError(
                        "device copy diverged from host mirror on clean rows"
                    )

    def _check_persistable(self, row: int) -> None:
        """With a store attached, reject un-persistable row ids BEFORE
        mutating so memory and op log can't diverge."""
        if self.store is not None:
            self.store.check_row(row)

    @contextmanager
    def _batched_store(self):
        """Coalesce one logical mutation's ops into single batch records
        (one locked append instead of one write+flush per bit)."""
        if self.store is None:
            yield
            return
        self.store.begin_batch()
        try:
            yield
        finally:
            self.store.end_batch()

    def _counts_delta(self, counts0, slots, deltas) -> None:
        """Carry the cached per-slot popcounts across a write (caller
        holds the lock and captured ``counts0 = self._counts`` BEFORE
        mutating — _touch/_slot null it), zero-padding for rows created
        by the write.  ``slots``/``deltas`` are a scalar pair (point
        write) or aligned arrays (import batch).  The ranked-cache role
        of reference cache.go:158/fragment.go:698-712: TopN keeps
        serving from maintained counts instead of rescanning."""
        if counts0 is None:
            return
        n = len(self._rowids)
        if len(counts0) < n:
            counts0 = np.concatenate(
                [counts0, np.zeros(n - len(counts0), dtype=np.int64)]
            )
        counts0[slots] += deltas
        self._counts = counts0

    def set_bit(self, row: int, col: int) -> bool:
        """Set bit (row, col-offset); returns True if it changed
        (reference fragment.go:645-713)."""
        with self._lock:
            self._check_persistable(row)
            counts0 = self._counts
            s = self._slot(row, create=True)
            w, b = col >> 5, np.uint32(1 << (col & 31))
            if self._host[s, w] & b:
                return False
            self._host[s, w] |= b
            self._delta_note_word(s, w)
            self._touch(s, tracked=True)
            self._counts_delta(counts0, s, 1)
            if self.store is not None:
                self.store.log_add(row, col)
            return True

    def clear_bit(self, row: int, col: int) -> bool:
        with self._lock:
            s = self._slot(row)
            if s is None:
                return False
            w, b = col >> 5, np.uint32(1 << (col & 31))
            if not self._host[s, w] & b:
                return False
            counts0 = self._counts
            self._host[s, w] &= ~b
            self._delta_note_word(s, w)
            self._touch(s, tracked=True)
            self._counts_delta(counts0, s, -1)
            if self.store is not None:
                self.store.log_remove(row, col)
            return True

    def get_bit(self, row: int, col: int) -> bool:
        with self._lock:
            s = self._slot_of.get(row)
            if s is None:
                return False
            return bool((int(self._host[s, col >> 5]) >> (col & 31)) & 1)

    def rows_with_column(self, col: int) -> list[int]:
        """Row ids containing this column — one vectorized pass over the
        host mirror's column word (the Rows(column=...) filter; reference
        fragment.go:2612-2657 filterColumn, without per-row get_bit)."""
        with self._lock:
            n = len(self._rowids)
            if n == 0:
                return []
            w, b = col >> 5, np.uint32(col & 31)
            mask = (self._host[:n, w] >> b) & np.uint32(1)
            return [self._rowids[s] for s in np.flatnonzero(mask)]

    def set_row_words(self, row: int, words: np.ndarray) -> bool:
        """Replace a whole row (reference fragment.go:781-834 setRow);
        returns True if the row changed."""
        with self._lock:
            self._check_persistable(row)
            s = self._slot(row, create=True)
            words = np.asarray(words, dtype=np.uint32)
            if np.array_equal(self._host[s], words):
                return False
            old = self._host[s].copy()
            self._host[s] = words
            self._delta_note_mask(s, old ^ words)
            self._touch(s, tracked=True)
            # log AFTER applying: a snapshot triggered mid-logging then
            # serializes the new state, against which these ops replay
            # idempotently
            if self.store is not None:
                added = words & ~old
                removed = old & ~words
                with self._batched_store():
                    if added.any():
                        self.store.log_add_mask(row, added)
                    if removed.any():
                        self.store.log_remove_mask(row, removed)
            return True

    def clear_row(self, row: int) -> bool:
        return self.set_row_words(row, np.zeros(self.n_words, dtype=np.uint32))

    def union_row_words(self, row: int, words: np.ndarray) -> int:
        """OR a word vector into a row; returns number of newly-set bits
        (the import-roaring merge unit, reference roaring.go:1463
        ImportRoaringBits)."""
        with self._lock:
            self._check_persistable(row)
            s = self._slot(row, create=True)
            words = np.asarray(words, dtype=np.uint32)
            added_mask = words & ~self._host[s]
            added = bitops.popcount_host(added_mask)
            if added:
                self._host[s] |= words
                self._delta_note_mask(s, added_mask)
                self._touch(s, tracked=True)
                if self.store is not None:
                    self.store.log_add_mask(row, added_mask)
            return added

    def difference_row_words(self, row: int, words: np.ndarray) -> int:
        """ANDNOT a word vector out of a row; returns bits cleared."""
        with self._lock:
            s = self._slot_of.get(row)
            if s is None:
                return 0
            words = np.asarray(words, dtype=np.uint32)
            removed_mask = words & self._host[s]
            removed = bitops.popcount_host(removed_mask)
            if removed:
                self._host[s] &= ~words
                self._delta_note_mask(s, removed_mask)
                self._touch(s, tracked=True)
                if self.store is not None:
                    self.store.log_remove_mask(row, removed_mask)
            return removed

    def import_bits(self, rows: np.ndarray, cols: np.ndarray, clear: bool = False) -> int:
        """Bulk import of (row, col-offset) pairs (reference
        fragment.go:1995-2106 bulkImport). Returns changed-bit count.

        The whole batch is applied as ONE vectorized masked update against
        the host mirror (the role of the reference's container-level merge,
        roaring.go:1463 ImportRoaringBits) — per-row Python work is limited
        to slot bookkeeping and op-log records for rows that changed."""
        rows = np.asarray(rows, dtype=np.uint64)
        cols = np.asarray(cols, dtype=np.int64)
        if rows.size == 0:
            return 0
        with self._lock, self._batched_store():
            counts0 = self._counts  # before slot creation nulls it
            # Group by row directly (never via row*width+col positions,
            # which would wrap uint64 for hashed row ids).
            row_ids = np.unique(rows)
            if clear:
                keep = np.array(
                    [int(r) in self._slot_of for r in row_ids], dtype=bool
                )
                if not keep.any():
                    return 0
                if not keep.all():
                    sel = keep[np.searchsorted(row_ids, rows)]
                    rows = rows[sel]
                    cols = cols[sel]
                    row_ids = row_ids[keep]
                for r in row_ids:  # BEFORE mutation: mirror/WAL atomicity
                    self._check_persistable(int(r))
                slots = np.array(
                    [self._slot_of[int(r)] for r in row_ids], dtype=np.int64
                )
            else:
                for r in row_ids:
                    self._check_persistable(int(r))
                slots = self._slots_batch(row_ids)
            # ONE sort of compact keys drives everything: dedup,
            # per-word grouping, changed-bit detection and WAL
            # positions all fall out — no dense [rows, n_words] mask
            # matrix and no unbuffered ufunc.at scalar loop.  The merge
            # itself is a single native pass when the toolchain exists
            # (hostops.cpp ph_import_merge: the roaring AddN/RemoveN
            # role, reference fragment.go:2052), with the vectorized
            # numpy pipeline as fallback.
            width = self.n_words * 32
            native = None
            if (
                _hostops.load() is not None
                and int(row_ids[-1]) <= (2**62) // width
            ):
                # id-keyed fast path: no inverse/searchsorted pass at
                # all — the native walk binary-searches row_ids once
                # per row run
                key = rows.astype(np.int64) * width + cols
                key.sort()
                native = _hostops.import_merge(
                    key, width, self.n_words, slots, row_ids,
                    self._host, clear, id_keys=True,
                    want_wal=self.store is not None,
                )
            if native is None:
                inverse = np.searchsorted(row_ids, rows)
                key = inverse.astype(np.int64) * width + cols
                key.sort()
                native = _hostops.import_merge(
                    key, width, self.n_words, slots, row_ids,
                    self._host, clear,
                    want_wal=self.store is not None,
                )
            if native is not None:
                n_changed, positions, per_row, changed_word_idx = native
                if n_changed:
                    for i in np.nonzero(per_row)[0]:
                        self._dirty.add(int(slots[i]))
                    if self._word_delta is not None:
                        self._delta_note(changed_word_idx)
                    if self.store is not None:
                        if clear:
                            self.store.log_remove_positions(positions)
                        else:
                            self.store.log_add_positions(positions)
                    self._counts_delta(
                        counts0, slots, -per_row if clear else per_row
                    )
                    self.version += 1
                    self.op_n += int(np.count_nonzero(per_row))
                    if self.on_op is not None:
                        self.on_op(self)
                return int(n_changed)
            ukey = np.unique(key)
            urow = ukey // width  # index into row_ids/slots
            ucol = ukey % width
            bitvals = np.uint32(1) << (ucol & 31).astype(np.uint32)
            # group bits into their words: wkey = urow*n_words + word
            wkey = ukey >> 5
            starts = np.flatnonzero(
                np.r_[True, wkey[1:] != wkey[:-1]]
            )
            wordvals = np.bitwise_or.reduceat(bitvals, starts)
            uw = wkey[starts]
            flat = self._host.reshape(-1)
            flat_idx = slots[uw // self.n_words] * self.n_words + uw % self.n_words
            pre_words = flat[flat_idx]
            if clear:
                changed_words = wordvals & pre_words
                flat[flat_idx] = pre_words & ~wordvals
            else:
                changed_words = wordvals & ~pre_words
                flat[flat_idx] = pre_words | wordvals
            # per-bit changed flags via the pre-update word of each key
            pre_of_key = pre_words[np.searchsorted(uw, wkey)]
            if clear:
                newly = (pre_of_key & bitvals) != 0
            else:
                newly = (pre_of_key & bitvals) == 0
            n_changed = int(np.count_nonzero(newly))
            if n_changed:
                ch_row = urow[newly]
                per_row = np.bincount(ch_row, minlength=len(row_ids))
                changed_idx = np.nonzero(per_row)[0]
                for i in changed_idx:
                    self._dirty.add(int(slots[i]))
                if self._word_delta is not None:
                    self._delta_note(flat_idx[changed_words != 0])
                if self.store is not None:
                    # WAL positions computed directly from the sorted
                    # keys (row-major, ascending — same record order the
                    # mask-unpack path produced); rows were
                    # check_row'd before the mutation above
                    positions = (
                        row_ids[ch_row].astype(np.uint64)
                        * np.uint64(width)
                        + ucol[newly].astype(np.uint64)
                    )
                    if clear:
                        self.store.log_remove_positions(positions)
                    else:
                        self.store.log_add_positions(positions)
                # carry the cached per-slot popcounts across the batch —
                # the per-row changed-bit counts are a by-product of the
                # merge, so TopN keeps serving without a rescan
                # (reference cache.go:158 ranked-cache maintenance)
                self._counts_delta(
                    counts0, slots, -per_row if clear else per_row
                )
                self.version += 1
                self.op_n += len(changed_idx)
                if self.on_op is not None:
                    self.on_op(self)
            return n_changed

    def set_mutex(self, row: int, col: int) -> bool:
        """Mutex-field write: clear col in every other row, set (row, col)
        (reference fragment.go:715-759 setBit w/ mutex vector,
        :3082-3152)."""
        with self._lock, self._batched_store():
            self._check_persistable(row)
            w, b = col >> 5, np.uint32(1 << (col & 31))
            target = self._slot(row, create=True)
            col_word = self._host[:, w]
            holders = np.flatnonzero(col_word & b)
            changed = False
            for s in holders:
                if s != target:
                    # via clear_bit so the op log sees the clears
                    changed |= self.clear_bit(self._rowids[int(s)], col)
            changed |= self.set_bit(row, col)
            return changed

    # -- device sync & query views -----------------------------------------

    def _device_nbytes(self) -> int:
        return (self.capacity + 1) * self.n_words * 4

    def device_declined(self) -> bool:
        """True when this fragment's full device copy alone would exceed
        the HBM budget cap — callers page rows from the host mirror
        instead of materializing it (the reference's mmap→file fallback,
        syswrap/mmap.go)."""
        return membudget.default_budget().would_decline(self._device_nbytes())

    def _budget_evict_cb(self):
        ref = weakref.ref(self)

        def cb():
            f = ref()
            if f is None:
                return
            # NON-BLOCKING acquire: the evicting thread may hold another
            # fragment's lock (its own admit), and that fragment's evict
            # callback may want ours — blocking here is an AB-BA deadlock
            # between two fragments under concurrent serving threads.
            # When contended, defer AND schedule a retry from a fresh
            # thread (which holds no locks, so a blocking acquire is
            # safe): without the retry, a fragment that is never queried
            # again would keep its HBM copy resident while the budget
            # reports the bytes reclaimed.
            if f._lock.acquire(blocking=False):
                try:
                    f._device = None
                    f._dirty.clear()
                    f._delta_reset()
                    # A concurrent device_bits may have re-admitted the
                    # entry between the budget's pop and this callback;
                    # drop that accounting with the copy (no-op in the
                    # common already-evicted case).
                    if f._budget_key is not None:
                        membudget.default_budget().release(f._budget_key)
                    residency.default_tracker().note_dropped(f)
                finally:
                    f._lock.release()
            else:
                f._evict_pending = True
                t = threading.Timer(0.05, _retry_evict, args=(ref,))
                t.daemon = True
                t.start()

        return cb

    def _account_device(self, rebuilt: bool) -> None:
        """Register/refresh the device copy with the process HBM budget
        (called under self._lock; budget lock nests inside)."""
        budget = membudget.default_budget()
        if self._budget_key is None:
            self._budget_key = membudget.register_owner(self, budget)
        if rebuilt:
            budget.admit(
                self._budget_key, self._device_nbytes(), self._budget_evict_cb()
            )
        else:
            budget.touch(self._budget_key)

    def device_bits(self) -> jax.Array:
        """The compute copy ``uint32[capacity+1, W]``; final row is zeros.
        Syncs pending host mutations to HBM first."""
        with self._lock:
            if self._evict_pending:
                self._evict_pending = False
                self._device = None
                self._dirty.clear()
                self._delta_reset()
            # residency outcome: was the compute copy already there when
            # this sync started?  (A dirty-row scatter still counts as a
            # hit — the query didn't pay the cold full upload.)
            was_resident = (
                self._device is not None
                and self._device.shape[0] == self.capacity + 1
            )
            rebuilt = False
            h2d = 0
            if self._device is None or self._device.shape[0] != self.capacity + 1:
                padded = np.zeros((self.capacity + 1, self.n_words), dtype=np.uint32)
                padded[: self.capacity] = self._host
                self._device = jnp.asarray(padded)
                self._dirty.clear()
                self._delta_reset()
                rebuilt = True
                h2d = padded.nbytes
            elif self._dirty:
                # choose the cheapest transfer: changed words (8 B each),
                # dirty rows (W*4 B each), or the full copy
                flat = None
                if self._word_delta is not None and (
                    (self.capacity + 1) * self.n_words < 2**31
                ):
                    flat = self._delta_flat()
                word_bytes = (
                    bitops.pow2_pad_len(len(flat)) * 8 if flat is not None else None
                )
                # past half the rows dirty, a wholesale device_put beats
                # the row scatter's host gather + jitted update, so the
                # row path's effective cost becomes the full copy
                prefer_full = len(self._dirty) > max(8, self.capacity // 2)
                full_bytes = (self.capacity + 1) * self.n_words * 4
                row_cost = (
                    full_bytes
                    if prefer_full
                    else bitops.pow2_pad_len(len(self._dirty)) * self.n_words * 4
                )
                if (
                    word_bytes is not None
                    and word_bytes <= row_cost
                    # empty delta with dirty slots would mean a tracked
                    # mutation forgot its note — never trust it; the
                    # row/full paths below handle it correctly
                    and len(flat)
                ):
                    idx = np.full(
                        bitops.pow2_pad_len(len(flat)), flat[0], np.int32
                    )
                    idx[: len(flat)] = flat.astype(np.int32)
                    vals = self._host.reshape(-1)[idx]
                    self._device = _scatter_words(
                        self._device, jnp.asarray(idx), jnp.asarray(vals)
                    )
                    h2d = idx.nbytes + vals.nbytes
                elif not prefer_full:
                    slots = np.fromiter(self._dirty, dtype=np.int32)
                    # Pad to a power-of-two bucket so the jitted scatter sees
                    # a bounded set of shapes (duplicate slot writes of the
                    # same data are harmless).
                    padded_slots = np.full(
                        bitops.pow2_pad_len(len(slots)), slots[0], dtype=np.int32
                    )
                    padded_slots[: len(slots)] = slots
                    self._device = _scatter_rows(
                        self._device,
                        jnp.asarray(padded_slots),
                        jnp.asarray(self._host[padded_slots]),
                    )
                    h2d = padded_slots.nbytes + (
                        len(padded_slots) * self.n_words * 4
                    )
                else:
                    padded = np.zeros(
                        (self.capacity + 1, self.n_words), dtype=np.uint32
                    )
                    padded[: self.capacity] = self._host
                    self._device = jnp.asarray(padded)
                    h2d = padded.nbytes
                self._dirty.clear()
                self._delta_reset()
            self.last_sync_h2d_bytes = h2d
            if h2d:
                kernels.note_transfer(h2d, "h2d")
            self._account_device(rebuilt)
            # hit/miss + heat feed the pin policy; prefetch-thread syncs
            # are accounted as prefetch traffic instead (residency.py)
            residency.default_tracker().note_sync(self, was_resident, h2d)
            return self._device

    def row_device(self, row: int) -> jax.Array:
        """One row's words on device; zeros when the row doesn't exist
        (reference fragment.go:599 ``row`` via roaring OffsetRange).

        When the whole fragment exceeds the HBM budget, only the one
        requested row is shipped (row paging)."""
        with self._lock:
            if self.device_declined():
                return jnp.asarray(self.row_words_host(row))
            bits = self.device_bits()
            s = self._slot_of.get(row, self.capacity)
        return bits[s]

    def rows_device(self, rows: Iterable[int]) -> jax.Array:
        """Gather many rows -> ``uint32[n, W]``; missing rows gather the
        zero row.  Pages just the requested rows when the fragment
        exceeds the HBM budget."""
        rows = list(rows)
        with self._lock:
            if self.device_declined():
                out = np.zeros((len(rows), self.n_words), dtype=np.uint32)
                for i, r in enumerate(rows):
                    s = self._slot_of.get(r)
                    if s is not None:
                        out[i] = self._host[s]
                return jnp.asarray(out)
            bits = self.device_bits()
            slots = np.array(
                [self._slot_of.get(r, self.capacity) for r in rows], dtype=np.int32
            )
        return bits[jnp.asarray(slots)]

    def row_words_host(self, row: int) -> np.ndarray:
        with self._lock:
            s = self._slot_of.get(row)
            if s is None:
                return np.zeros(self.n_words, dtype=np.uint32)
            return self._host[s].copy()

    def row_columns(self, row: int) -> np.ndarray:
        """Sorted column offsets of a row (host materialization)."""
        return bitops.unpack_columns(self.row_words_host(row))

    def rows_matrix_host(self) -> tuple[list[int], np.ndarray]:
        """(row_ids, words[len(row_ids), W]) — one copy of every present
        row in slot order, for bulk consumers (serving-stack builds) that
        would otherwise pay a Python call + copy per row."""
        with self._lock:
            n = len(self._rowids)
            return list(self._rowids), self._host[:n].copy()

    def row_count(self, row: int) -> int:
        with self._lock:
            s = self._slot_of.get(row)
            if s is None:
                return 0
            return bitops.popcount_host(self._host[s])

    def row_pair_count(self, ra: int, rb: int, op: str) -> int:
        """Fused ``popcount(op(row_a, row_b))`` from the host mirror,
        zero-copy under the fragment lock — the latency tier for a lone
        ``Count(op(Row, Row))`` (reference roaring.go:568; the batched
        throughput tier is the device gram, ops/kernels.py).  ``op`` is
        one of intersect/union/difference/xor; absent rows count as
        zero rows."""
        with self._lock:
            sa = self._slot_of.get(ra)
            sb = self._slot_of.get(rb)
            if sa is None and sb is None:
                return 0
            if sa is None:
                if op == "difference":
                    return 0
                if op == "intersect":
                    return 0
                return bitops.popcount_host(self._host[sb])
            if sb is None:
                if op == "intersect":
                    return 0
                return bitops.popcount_host(self._host[sa])
            return bitops.pair_count_host(self._host[sa], self._host[sb], op)

    def row_counts(self) -> tuple[list[int], np.ndarray]:
        """(row_ids, per-row popcounts) over existing rows — the TopN
        ranked-cache analogue (reference cache.go).  Counts are
        MAINTAINED across writes (point deltas and import batches carry
        them, like the reference's incremental cache updates,
        fragment.go:698-712) and recomputed from the host mirror only
        when absent — never a device round trip, so a lone TopN stays
        in the latency tier."""
        with self._lock:
            if self._counts is None or len(self._counts) != len(self._rowids):
                n = len(self._rowids)
                self._counts = np.bitwise_count(self._host[:n]).sum(
                    axis=1, dtype=np.int64
                )
            ids = list(self._rowids)
            return ids, self._counts.copy()

    # -- BSI (bit-sliced integer) operations -------------------------------

    def bsi_tensors(self, bit_depth: int):
        """(planes[bit_depth, W], exists, sign) device tensors for BSI
        kernels; missing planes gather zeros."""
        planes = self.rows_device(
            range(BSI_OFFSET_BIT, BSI_OFFSET_BIT + bit_depth)
        )
        exists = self.row_device(BSI_EXISTS_BIT)
        sign = self.row_device(BSI_SIGN_BIT)
        return planes, exists, sign

    def fill_bsi_tensors_host(
        self, bit_depth: int, planes_out, exists_out, sign_out
    ) -> None:
        """Host-mirror twin of :func:`bsi_tensors`: fill CALLER-OWNED
        arrays (planes_out[bit_depth, W], exists_out[W], sign_out[W],
        zero-initialized) from the mirror — the latency tier
        preallocates one stacked buffer for all fragments, so a lone
        cold BSI predicate costs exactly one field-sized host copy."""
        with self._lock:
            for k in range(bit_depth):
                s = self._slot_of.get(BSI_OFFSET_BIT + k)
                if s is not None:
                    planes_out[k] = self._host[s]
            se = self._slot_of.get(BSI_EXISTS_BIT)
            if se is not None:
                exists_out[:] = self._host[se]
            ss = self._slot_of.get(BSI_SIGN_BIT)
            if ss is not None:
                sign_out[:] = self._host[ss]

    def bsi_tensors_host(self, bit_depth: int):
        """(planes[bit_depth, W], exists, sign) numpy copies — the
        allocate-per-fragment convenience over
        :func:`fill_bsi_tensors_host`."""
        planes = np.zeros((bit_depth, self.n_words), dtype=np.uint32)
        exists = np.zeros(self.n_words, dtype=np.uint32)
        sign = np.zeros(self.n_words, dtype=np.uint32)
        self.fill_bsi_tensors_host(bit_depth, planes, exists, sign)
        return planes, exists, sign

    def set_value(self, col: int, bit_depth: int, value: int) -> bool:
        """Write a stored (already base-offset) value for a column
        (reference fragment.go:929-1003 setValueBase)."""
        with self._lock, self._batched_store():
            changed = self.set_bit(BSI_EXISTS_BIT, col)
            mag = abs(value)
            if value < 0:
                changed |= self.set_bit(BSI_SIGN_BIT, col)
            else:
                changed |= self.clear_bit(BSI_SIGN_BIT, col)
            for k in range(bit_depth):
                if (mag >> k) & 1:
                    changed |= self.set_bit(BSI_OFFSET_BIT + k, col)
                else:
                    changed |= self.clear_bit(BSI_OFFSET_BIT + k, col)
            return changed

    def value(self, col: int, bit_depth: int) -> tuple[int, bool]:
        """(stored value, exists) for a column (reference
        fragment.go:894-927)."""
        with self._lock:
            if not self.get_bit(BSI_EXISTS_BIT, col):
                return 0, False
            mag = 0
            for k in range(bit_depth):
                if self.get_bit(BSI_OFFSET_BIT + k, col):
                    mag |= 1 << k
            if self.get_bit(BSI_SIGN_BIT, col):
                mag = -mag
            return mag, True

    def clear_value(self, col: int) -> bool:
        """Remove a column's BSI value entirely — one masked pass over
        the plane rows' column word instead of a per-row clear_bit loop."""
        with self._lock, self._batched_store():
            s_exists = self._slot_of.get(BSI_EXISTS_BIT)
            w, bmask = col >> 5, np.uint32(1 << (col & 31))
            if s_exists is None or not self._host[s_exists, w] & bmask:
                return False
            n = len(self._rowids)
            set_slots = np.flatnonzero(self._host[:n, w] & bmask)
            self._host[set_slots, w] &= ~bmask
            for s in set_slots.tolist():
                self._delta_note_word(int(s), w)
                self._touch(int(s), tracked=True)
                if self.store is not None:
                    self.store.log_remove(self._rowids[s], col)
            return True

    def import_values(self, cols: np.ndarray, values: np.ndarray, bit_depth: int, clear: bool = False) -> None:
        """Bulk BSI import (reference fragment.go:2107-2200 importValue):
        per-plane vectorized writes instead of per-bit loops."""
        cols = np.asarray(cols, dtype=np.int64)
        values = np.asarray(values, dtype=np.int64)
        if cols.size == 0:
            return
        # Last write wins for duplicate columns within a batch (the
        # reference applies batch entries sequentially, same outcome).
        last = len(cols) - 1 - np.unique(cols[::-1], return_index=True)[1]
        cols, values = cols[last], values[last]
        with self._lock, self._batched_store():
            col_words = bitops.pack_columns(cols, self.n_words)
            if clear:
                for row in list(self._slot_of):
                    self.difference_row_words(row, col_words)
                return
            mags = np.abs(values)
            # exists plane: OR in all columns
            self.union_row_words(BSI_EXISTS_BIT, col_words)
            # sign plane: set for negative, clear for non-negative
            neg_words = bitops.pack_columns(cols[values < 0], self.n_words)
            pos_words = col_words & ~neg_words
            self.union_row_words(BSI_SIGN_BIT, neg_words)
            self.difference_row_words(BSI_SIGN_BIT, pos_words)
            for k in range(bit_depth):
                on = bitops.pack_columns(cols[(mags >> k) & 1 == 1], self.n_words)
                off = col_words & ~on
                self.union_row_words(BSI_OFFSET_BIT + k, on)
                self.difference_row_words(BSI_OFFSET_BIT + k, off)

    # -- whole-fragment helpers --------------------------------------------

    def to_host_rows(self) -> dict[int, np.ndarray]:
        """row id -> packed words snapshot (dropping all-zero rows), the
        snapshot payload (reference fragment.go:2325-2381)."""
        with self._lock:
            out = {}
            for row, s in self._slot_of.items():
                if self._host[s].any():
                    out[row] = self._host[s].copy()
            return out

    def snapshot_rows(self) -> tuple[np.ndarray, np.ndarray]:
        """(ascending row ids uint64, stacked words [n, n_words]) — the
        snapshot source as ONE fancy-index copy under the lock
        (to_host_rows + np.stack would copy the mirror twice).
        All-zero rows are kept; they serialize to zero containers."""
        with self._lock:
            if not self._slot_of:
                return (
                    np.empty(0, dtype=np.uint64),
                    np.empty((0, self.n_words), dtype=np.uint32),
                )
            rids = np.array(sorted(self._slot_of), dtype=np.uint64)
            slots = np.array(
                [self._slot_of[int(r)] for r in rids], dtype=np.int64
            )
            return rids, self._host[slots]

    def load_host_rows(self, rows: dict[int, np.ndarray]) -> None:
        with self._lock:
            self._slot_of.clear()
            self._rowids.clear()
            self._set_host(np.zeros((0, self.n_words), dtype=np.uint32))
            self._drop_device()
            self._counts = None
            self.version += 1
            for row in sorted(rows):
                s = self._slot(row, create=True)
                self._host[s] = np.asarray(rows[row], dtype=np.uint32)
            self.op_n = 0

    def total_count(self) -> int:
        with self._lock:
            return bitops.popcount_host(self._host)

    def all_positions(self) -> np.ndarray:
        """Sorted absolute bit positions row*width + col of every set bit
        (the whole-fragment interchange payload, reference
        fragment.go:2424-2594 WriteTo)."""
        with self._lock:
            parts = []
            width = np.uint64(self.shard_width)
            for row in sorted(self._slot_of):
                cols = bitops.unpack_columns(self._host[self._slot_of[row]])
                if len(cols):
                    parts.append(cols.astype(np.uint64) + np.uint64(row) * width)
            if not parts:
                return np.array([], dtype=np.uint64)
            return np.concatenate(parts)

    def container_profile(self, containers: bool = True) -> dict:
        """Storage-shape stats — set-bit total, bit density, and (when
        ``containers``) the roaring container census — cached under the
        fragment's ``(epoch, version)`` mutation pair, so repeat readers
        (``/debug/fragments``, the flight planner's selectivity model)
        pay a dict lookup instead of a rescan while the fragment is
        unchanged.  ``containers=False`` skips the O(bits) position
        unpack the census needs — the planner prices subtrees on every
        flight, and write-heavy workloads bump versions too often to
        amortize a census per flight; the census is computed lazily and
        folded into the same cached dict on the first full request.
        The whole compute runs under the fragment lock (RLock; the
        helpers retake it) so the cached stats always describe exactly
        one version."""
        from pilosa_tpu.storage import roaring

        with self._lock:
            key = (self.epoch, self.version)
            cached = self._container_profile
            if cached is not None and cached[0] == key:
                prof = cached[1]
            else:
                bits = self.total_count()
                prof = {
                    "bits": bits,
                    "rows": len(self._slot_of),
                    "density": (
                        bits / (len(self._slot_of) * self.shard_width)
                        if self._slot_of
                        else 0.0
                    ),
                }
                self._container_profile = (key, prof)
            if containers and "containers" not in prof:
                prof["containers"] = roaring.container_stats(
                    self.all_positions()
                )
            return prof

    # -- anti-entropy blocks (reference fragment.go:1760-1991) --------------

    def blocks(self) -> list[dict]:
        """Checksums of HashBlockSize-row blocks; blocks with no bits are
        omitted (reference fragment.go Blocks/blockChecksum)."""
        from pilosa_tpu.core import blockhash

        with self._lock:
            by_block: dict[int, list[int]] = {}
            for row in sorted(self._slot_of):
                if self._host[self._slot_of[row]].any():
                    by_block.setdefault(row // HASH_BLOCK_SIZE, []).append(row)
            out = []
            for block in sorted(by_block):
                h = blockhash.new_hash()
                for row in by_block[block]:
                    blockhash.add_row(h, row, self._host[self._slot_of[row]])
                out.append({"id": block, "checksum": h.hexdigest()})
            return out

    def block_data(self, block: int) -> tuple[list[int], list[int]]:
        """(rows, cols) pairs of every set bit in a block, local
        coordinates, row-major (reference fragment.go blockData)."""
        with self._lock:
            rows_out: list[int] = []
            cols_out: list[int] = []
            lo = block * HASH_BLOCK_SIZE
            for row in range(lo, lo + HASH_BLOCK_SIZE):
                slot = self._slot_of.get(row)
                if slot is None:
                    continue
                cols = bitops.unpack_columns(self._host[slot])
                rows_out.extend([row] * len(cols))
                cols_out.extend(int(c) for c in cols)
            return rows_out, cols_out
