"""Block checksum hashing for anti-entropy (reference fragment.go:81,
1760-1839: 100-row blocks, xxhash64 over row/col pairs).

blake2b (8-byte digest, stdlib) stands in for xxhash64 — the checksum
only needs to be deterministic across nodes and cheap; it never leaves
the cluster.
"""

from __future__ import annotations

import hashlib

import numpy as np


def new_hash():
    return hashlib.blake2b(digest_size=8)


def add_row(h, row: int, words: np.ndarray) -> None:
    h.update(row.to_bytes(8, "little"))
    h.update(np.ascontiguousarray(words, dtype=np.uint32).tobytes())
