"""Index: a container of fields (reference: index.go).

Per-index options: ``keys`` (string column keys) and ``trackExistence``
(reference index.go:476-479). With trackExistence an internal ``_exists``
field records every column ever set, powering ``Not()`` and existence
queries (reference index.go:173-180 openExistenceField, holder.go:46
existenceFieldName)."""

from __future__ import annotations

import itertools
import threading

from pilosa_tpu.core.attrs import AttrStore
from pilosa_tpu.core.field import Field, FieldOptions, validate_name
from pilosa_tpu.obs import stats as stats_mod
from pilosa_tpu.shardwidth import SHARD_WORDS

EXISTENCE_FIELD_NAME = "_exists"


class Index:
    # process-unique creation sequence: a dropped-and-recreated index of
    # the same name must never alias cache keys of its predecessor
    # (exec/rescache.py keys on it)
    _SEQ = itertools.count()

    def __init__(
        self,
        name: str,
        keys: bool = False,
        track_existence: bool = True,
        n_words: int = SHARD_WORDS,
    ):
        validate_name(name)
        self.name = name
        self.keys = keys
        self.track_existence = track_existence
        self.n_words = n_words
        self._lock = threading.RLock()
        self.seq = next(Index._SEQ)
        # schema generation: bumped on field create/delete so semantic
        # cache keys built against the old field set can't survive a
        # schema change (exec/rescache.py)
        self.generation = 0
        self.fields: dict[str, Field] = {}
        # column attributes (reference index.go columnAttrs boltdb store)
        self.column_attrs = AttrStore()
        self.on_create_field = None
        self.stats = stats_mod.NOP
        if track_existence:
            self._create_existence_field()

    def set_stats(self, client) -> None:
        with self._lock:
            self.stats = client
            for name, f in self.fields.items():
                f.stats = client.with_tags(f"field:{name}")

    def _create_existence_field(self) -> Field:
        f = Field(self.name, EXISTENCE_FIELD_NAME, n_words=self.n_words)
        self.fields[EXISTENCE_FIELD_NAME] = f
        return f

    def existence_field(self) -> Field | None:
        return self.fields.get(EXISTENCE_FIELD_NAME)

    def field(self, name: str) -> Field | None:
        return self.fields.get(name)

    def create_field(self, name: str, options: FieldOptions | None = None) -> Field:
        """reference index.go:303-367 CreateField."""
        with self._lock:
            if name in self.fields:
                raise ValueError(f"field already exists: {name}")
            f = Field(self.name, name, options, self.n_words)
            f.stats = self.stats.with_tags(f"field:{name}")
            self.fields[name] = f
            self.generation += 1
            if self.on_create_field is not None:
                self.on_create_field(self, f)
            return f

    def create_field_if_not_exists(self, name: str, options: FieldOptions | None = None) -> Field:
        with self._lock:
            f = self.fields.get(name)
            if f is None:
                return self.create_field(name, options)
            return f

    def delete_field(self, name: str) -> bool:
        """reference index.go:430-453."""
        with self._lock:
            gone = self.fields.pop(name, None) is not None
            if gone:
                self.generation += 1
            return gone

    def field_names(self, include_internal: bool = False) -> list[str]:
        return sorted(
            n for n in self.fields if include_internal or not n.startswith("_")
        )

    def available_shards(self) -> set[int]:
        """Union over fields (reference index.go:244-259)."""
        shards: set[int] = set()
        for f in self.fields.values():
            shards |= f.available_shards()
        return shards

    def add_column_existence(self, col: int) -> None:
        """Mark a column as existing (reference executor.go:2098-2103)."""
        ef = self.existence_field()
        if ef is not None:
            ef.set_bit(0, col)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "options": {"keys": self.keys, "trackExistence": self.track_existence},
            "fields": [
                self.fields[n].to_dict() for n in self.field_names()
            ],
        }
