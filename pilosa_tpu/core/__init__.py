"""Data-model hierarchy: holder -> index -> field -> view -> fragment
(reference: holder.go, index.go, field.go, view.go, fragment.go)."""
