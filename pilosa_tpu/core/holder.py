"""Holder: the root container of indexes (reference: holder.go:50).

Memory-resident here; the storage layer (pilosa_tpu.storage) adds the
on-disk directory tree + snapshot/op-log persistence the reference keeps
under its data dir (reference holder.go:134-198 Open)."""

from __future__ import annotations

import threading

from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.index import Index
from pilosa_tpu.obs import stats as stats_mod
from pilosa_tpu.shardwidth import SHARD_WORDS


class Holder:
    def __init__(self, n_words: int = SHARD_WORDS):
        self.n_words = n_words
        self._lock = threading.RLock()
        self.indexes: dict[str, Index] = {}
        self.on_create_index = None
        # Injected metrics sink (reference holder.go Stats, default nop).
        self.stats = stats_mod.NOP
        # Control-plane observability: cluster event journal + background
        # job tracker, shared by cluster/storage/server layers the same
        # way stats is.
        from pilosa_tpu.obs.events import EventJournal
        from pilosa_tpu.obs.jobs import JobTracker
        from pilosa_tpu.obs.slo import SLOTracker
        from pilosa_tpu.obs.tracestore import TraceStore

        self.events = EventJournal()
        self.jobs = JobTracker()
        # SLO plane: per-op-class latency quantiles + error budgets,
        # recorded at the HTTP boundary, served at /debug/slo.
        self.slo = SLOTracker()
        # Trace plane: tail-sampled per-node trace store (/debug/traces);
        # slow-keep thresholds come from the SLO latency objectives, and
        # kept traces feed the SLO histogram's bucket exemplars.
        self.traces = TraceStore(slo=self.slo)
        self.traces.on_keep = self.slo.attach_exemplar

    def set_stats(self, client: stats_mod.StatsClient) -> None:
        """Install a stats client, re-tagging existing indexes/fields the
        way the reference wires stats at construction (holder.go:112)."""
        with self._lock:
            self.stats = client
            self.jobs.stats = client
            for name, idx in self.indexes.items():
                idx.set_stats(client.with_tags(f"index:{name}"))

    def index(self, name: str) -> Index | None:
        return self.indexes.get(name)

    def create_index(
        self, name: str, keys: bool = False, track_existence: bool = True
    ) -> Index:
        with self._lock:
            if name in self.indexes:
                raise ValueError(f"index already exists: {name}")
            idx = Index(name, keys=keys, track_existence=track_existence, n_words=self.n_words)
            idx.set_stats(self.stats.with_tags(f"index:{name}"))
            self.indexes[name] = idx
            if self.on_create_index is not None:
                self.on_create_index(idx)
            return idx

    def create_index_if_not_exists(
        self, name: str, keys: bool = False, track_existence: bool = True
    ) -> Index:
        with self._lock:
            idx = self.indexes.get(name)
            if idx is None:
                return self.create_index(name, keys, track_existence)
            return idx

    def delete_index(self, name: str) -> bool:
        with self._lock:
            return self.indexes.pop(name, None) is not None

    def index_names(self) -> list[str]:
        return sorted(self.indexes)

    def field(self, index: str, field: str):
        idx = self.index(index)
        return idx.field(field) if idx is not None else None

    def fragment(self, index: str, field: str, view: str, shard: int) -> Fragment | None:
        """Direct fragment accessor (reference holder.go:496-502)."""
        f = self.field(index, field)
        if f is None:
            return None
        v = f.view(view)
        return v.fragment(shard) if v is not None else None

    def schema(self) -> list[dict]:
        """reference holder.go:279-299 Schema."""
        return [self.indexes[n].to_dict() for n in self.index_names()]

    def apply_schema(self, schema: list[dict]) -> None:
        """Create all indexes/fields described (reference holder.go:318-345
        applySchema)."""
        from pilosa_tpu.core.field import FieldOptions

        for idx_d in schema:
            opts = idx_d.get("options", {})
            idx = self.create_index_if_not_exists(
                idx_d["name"],
                keys=opts.get("keys", False),
                track_existence=opts.get("trackExistence", True),
            )
            for f_d in idx_d.get("fields", []):
                if f_d["name"].startswith("_"):
                    continue
                idx.create_field_if_not_exists(
                    f_d["name"], FieldOptions.from_dict(f_d.get("options", {}))
                )
