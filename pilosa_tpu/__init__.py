"""pilosa_tpu — a TPU-native distributed bitmap index.

A from-scratch framework with the capabilities of Pilosa (the Go reference
lives at /root/reference): a distributed bitmap index with the PQL query
language, set/int(BSI)/time/mutex/bool fields, time-quantum views, TopN
ranked caches, key translation, row/column attributes, replication and
cluster membership — redesigned for TPU:

* fragments are dense HBM-resident bitmap tensors (``uint32[rows, words]``)
  instead of mmap'd roaring bitmaps; roaring survives only as the
  storage/interchange codec,
* the per-container op matrix (reference roaring/roaring.go:3078-4414)
  collapses to vectorized AND/OR/XOR/ANDNOT + popcount XLA/Pallas kernels,
* the executor compiles PQL ASTs to jitted XLA computations instead of Go
  loops, and cross-shard map-reduce (reference executor.go:2454-2611) runs
  as shard_map over a ``jax.sharding.Mesh`` with ICI collectives instead of
  HTTP/protobuf fan-out.
"""

__version__ = "0.3.0"

from pilosa_tpu.shardwidth import SHARD_WIDTH, SHARD_WIDTH_EXP

__all__ = [
    "SHARD_WIDTH",
    "SHARD_WIDTH_EXP",
    "__version__",
]
