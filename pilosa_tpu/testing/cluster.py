"""Multi-node in-process cluster for tests (reference: test/pilosa.go
MustRunCluster :344-400, test/cluster.go).

Boots n real ``NodeServer`` processes-in-threads with real HTTP
listeners on auto-bound ports, fixes static membership (node 0 is the
coordinator), and exposes the same conveniences as the reference's
``test.Cluster``: schema creation through any node, shard-routed bit
imports, and queries against every node.
"""

from __future__ import annotations

import tempfile
import urllib.parse

from pilosa_tpu.server.node import NodeServer
from pilosa_tpu.shardwidth import SHARD_WORDS
from pilosa_tpu.testing import faults


class InProcessCluster:
    def __init__(
        self,
        n: int,
        replica_n: int = 1,
        n_words: int = SHARD_WORDS,
        with_disk: bool = False,
        long_query_time: float = 0.0,
        slow_query_time: float = 0.0,
        import_workers: int = 2,
        import_queue_depth: int = 16,
        ingest_staging_buffers: int = 4,
        ingest_upload_slots: int = 2,
        slo_objectives: dict | None = None,
        slo_burn_rules: list[dict] | None = None,
        slo_slot_seconds: float | None = None,
        slo_latency_window: float | None = None,
        default_deadline: float = 0.0,
        trace_store_capacity: int = 256,
        trace_baseline_n: int = 128,
        flight_recorder: bool = True,
        flightrec_segment_seconds: float = 1.0,
        flightrec_sample_interval: float = 0.025,
        flightrec_segments: int = 60,
        flightrec_spike_504: int = 5,
        history_enabled: bool = True,
        history_cadence: float = 1.0,
        history_tiers: str = "300@1,240@15",
        history_detectors: str = "latency,throughput,errors",
        history_warmup: int = 10,
        history_trips: int = 3,
        history_latency_factor: float = 2.0,
        history_latency_min_ms: float = 20.0,
        mesh_dispatch: bool = True,
        rescache_entries: int = 512,
        rescache_promote_hits: int = 3,
        rescache_demote_deltas: int = 64,
        planner_enabled: bool = True,
        qos_enabled: bool = True,
        qos_weights: dict | None = None,
        qos_down_factor: float = 8.0,
        qos_stage_hold: float = 2.0,
        qos_relax_hold: float = 5.0,
        qos_tick_interval: float = 0.25,
        qos_retry_after: float = 1.0,
        qos_aggressor_share: float = 0.5,
        blackbox_enabled: bool = True,
        blackbox_interval: float = 5.0,
        blackbox_max_segments: int = 64,
        blackbox_max_bytes: int = 16 << 20,
        blackbox_keep_postmortems: int = 4,
        blackbox_history_window: float = 60.0,
    ):
        self._tmp = tempfile.TemporaryDirectory() if with_disk else None
        self.nodes: list[NodeServer] = []
        self._slow_query_time = slow_query_time
        self._ingest_knobs = {
            # In-process nodes share one device mesh, so cluster-on-mesh
            # dispatch (cluster/dist.py) is exercised by default; tests
            # that assert on the HTTP fan-out plane pass False.
            "mesh_dispatch": mesh_dispatch,
            "import_workers": import_workers,
            "import_queue_depth": import_queue_depth,
            "ingest_staging_buffers": ingest_staging_buffers,
            "ingest_upload_slots": ingest_upload_slots,
            "slo_objectives": slo_objectives,
            "slo_burn_rules": slo_burn_rules,
            "slo_slot_seconds": slo_slot_seconds,
            "slo_latency_window": slo_latency_window,
            "default_deadline": default_deadline,
            "trace_store_capacity": trace_store_capacity,
            "trace_baseline_n": trace_baseline_n,
            "flight_recorder": flight_recorder,
            "flightrec_segment_seconds": flightrec_segment_seconds,
            "flightrec_sample_interval": flightrec_sample_interval,
            "flightrec_segments": flightrec_segments,
            "flightrec_spike_504": flightrec_spike_504,
            "history_enabled": history_enabled,
            "history_cadence": history_cadence,
            "history_tiers": history_tiers,
            "history_detectors": history_detectors,
            "history_warmup": history_warmup,
            "history_trips": history_trips,
            "history_latency_factor": history_latency_factor,
            "history_latency_min_ms": history_latency_min_ms,
            "rescache_entries": rescache_entries,
            "rescache_promote_hits": rescache_promote_hits,
            "rescache_demote_deltas": rescache_demote_deltas,
            "planner_enabled": planner_enabled,
            "qos_enabled": qos_enabled,
            "qos_weights": qos_weights,
            "qos_down_factor": qos_down_factor,
            "qos_stage_hold": qos_stage_hold,
            "qos_relax_hold": qos_relax_hold,
            "qos_tick_interval": qos_tick_interval,
            "qos_retry_after": qos_retry_after,
            "qos_aggressor_share": qos_aggressor_share,
            # black box only engages on with_disk clusters (a diskless
            # node has nowhere to survive a crash)
            "blackbox_enabled": blackbox_enabled,
            "blackbox_interval": blackbox_interval,
            "blackbox_max_segments": blackbox_max_segments,
            "blackbox_max_bytes": blackbox_max_bytes,
            "blackbox_keep_postmortems": blackbox_keep_postmortems,
            "blackbox_history_window": blackbox_history_window,
        }
        # Monotonic so a node added after a removal never reuses a live
        # node's data dir (dirs are keyed by birth order, not list index).
        self._next_node_num = n
        for i in range(n):
            data_dir = f"{self._tmp.name}/node{i}" if self._tmp else None
            node = NodeServer(
                data_dir=data_dir,
                replica_n=replica_n,
                n_words=n_words,
                long_query_time=long_query_time,
                slow_query_time=slow_query_time,
                **self._ingest_knobs,
            )
            node.start()
            self.nodes.append(node)
        members = [(s.node_id, s.uri) for s in self.nodes]
        members.sort()
        self.coordinator_id = self.nodes[0].node_id
        for s in self.nodes:
            s.join_static(members, self.coordinator_id)
        self._faults: faults.FaultRegistry | None = None

    def __enter__(self) -> "InProcessCluster":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __len__(self) -> int:
        return len(self.nodes)

    def __getitem__(self, i: int) -> NodeServer:
        return self.nodes[i]

    @property
    def coordinator(self) -> NodeServer:
        for s in self.nodes:
            if s.node_id == self.coordinator_id:
                return s
        raise RuntimeError("coordinator not in cluster")

    # -- conveniences (reference test/cluster.go) ---------------------------

    def create_index(self, name: str, options: dict | None = None) -> None:
        self.nodes[0].api.create_index(name, options or {})

    def create_field(self, index: str, field: str, options: dict | None = None) -> None:
        self.nodes[0].api.create_field(index, field, options or {})

    def query(self, node: int, index: str, pql: str, profile: bool = False) -> dict:
        return self.nodes[node].api.query(index, pql, profile=profile)

    def import_bits(self, index: str, field: str, bits: list[tuple[int, int]]) -> None:
        """Route (row, col) pairs through node 0's import coordinator
        (reference test/pilosa.go ImportBits :256-294 routes to owners)."""
        self.nodes[0].api.import_bits(
            index,
            field,
            {
                "rowIDs": [r for r, _ in bits],
                "columnIDs": [c for _, c in bits],
            },
        )

    def import_values(
        self, index: str, field: str, cols: list[int], values: list[int]
    ) -> None:
        """Route (col, value) pairs into an int field through node 0's
        import coordinator (the BSI twin of :meth:`import_bits`)."""
        self.nodes[0].api.import_bits(
            index,
            field,
            {"columnIDs": list(cols), "values": list(values)},
        )

    def owner_of(self, index: str, shard: int) -> NodeServer:
        node_id = self.nodes[0].cluster.primary_shard_node(index, shard).id
        for s in self.nodes:
            if s.node_id == node_id:
                return s
        raise RuntimeError("owner not found")

    def add_node(self) -> NodeServer:
        """Boot a fresh node and resize it into the cluster through the
        coordinator (reference server/cluster_test.go node-join tests)."""
        data_dir = (
            f"{self._tmp.name}/node{self._next_node_num}" if self._tmp else None
        )
        self._next_node_num += 1
        node = NodeServer(
            data_dir=data_dir,
            replica_n=self.nodes[0].cluster.replica_n,
            n_words=self.nodes[0].holder.n_words,
            long_query_time=self.nodes[0].server.httpd.RequestHandlerClass.long_query_time,
            slow_query_time=self._slow_query_time,
            **self._ingest_knobs,
        )
        node.start()
        try:
            self.coordinator.resize_coordinator().add_node(node.node_id, node.uri)
        except Exception:
            node.stop()
            raise
        self.nodes.append(node)
        return node

    def remove_node(self, i: int) -> None:
        node = self.nodes[i]
        self.coordinator.resize_coordinator().remove_node(node.node_id)
        node.stop()
        self.nodes.pop(i)

    def sync_all(self) -> dict:
        """Run one anti-entropy pass on every node; returns summed stats."""
        total: dict[str, int] = {}
        for n in self.nodes:
            for k, v in n.syncer().sync_holder().items():
                total[k] = total.get(k, 0) + v
        return total

    # -- deterministic fault injection (testing/faults.py) -------------------

    def fault_registry(self, seed: int = 0) -> faults.FaultRegistry:
        """The cluster's installed fault registry (created + installed
        lazily; ``seed`` only applies to the first call).  Every rule
        firing is journaled on the coordinator so chaos runs read as one
        timeline: fault fired -> breaker opened -> job aborted."""
        if self._faults is None:
            self._faults = faults.install(faults.FaultRegistry(seed=seed))
            from pilosa_tpu.obs import events as ev

            journal = self.nodes[0].holder.events if self.nodes else None
            if journal is not None:
                self._faults.on_fire = lambda kind, target: journal.record(
                    ev.EVENT_FAULT_INJECTED, kind=kind, target=target
                )
        return self._faults

    def inject_fault(
        self,
        kind: str,
        node: int | None = None,
        peer: str | None = None,
        route: str | None = None,
        path: str | None = None,
        stage: str | None = None,
        delay: float = 0.0,
        code: int = 503,
        times: int | None = None,
        p: float = 1.0,
        seed: int = 0,
    ) -> faults.Fault:
        """Add one fault rule; returns it for later ``remove``/``hits``
        inspection.  ``node`` is an index into ``self.nodes`` and is
        shorthand for ``peer=<that node's netloc>`` (network kinds) —
        use ``peer``/``route``/``path`` fnmatch patterns for anything
        finer.  Example::

            cl.inject_fault("reset", node=1, route="/index/*", times=2)
            cl.inject_fault("slow", node=2, delay=5.0)
            cl.inject_fault("disk_write_fail", path="*/ci/cf/*")
        """
        if node is not None:
            if peer is not None:
                raise ValueError("pass node OR peer, not both")
            peer = urllib.parse.urlsplit(self.nodes[node].uri).netloc
        return self.fault_registry(seed=seed).add(
            kind, peer=peer, route=route, path=path, stage=stage,
            delay=delay, code=code, times=times, p=p,
        )

    def clear_faults(self) -> None:
        if self._faults is not None:
            self._faults.clear()

    def stop_node(self, i: int) -> None:
        """Hard-stop one node (fault injection — the reference uses pumba
        pause in internal/clustertests)."""
        self.nodes[i].stop()

    def pause_node(self, i: int) -> None:
        """Make a node drop all requests without stopping it (the pumba
        pause analogue: process alive, network dead)."""
        self.nodes[i].server.pause()

    def resume_node(self, i: int) -> None:
        self.nodes[i].server.resume()

    def close(self) -> None:
        if self._faults is not None:
            faults.uninstall(self._faults)
            self._faults = None
        for s in self.nodes:
            try:
                s.stop()
            except Exception:  # graftlint: disable=exception-hygiene -- harness teardown: a node the test already killed must not abort cleanup of the rest
                pass
        if self._tmp is not None:
            self._tmp.cleanup()
