"""In-process test harness (reference: test/ package, 1000 LoC —
test.MustRunCluster boots n real nodes with real transport on port 0,
test/pilosa.go:344-400) plus the deterministic fault-injection registry
(``pilosa_tpu.testing.faults``).

``InProcessCluster`` is re-exported lazily: production modules
(cluster/client.py, storage/fragmentfile.py) import
``pilosa_tpu.testing.faults`` for their fault hook points, and an eager
import here would cycle back through server/node.py into the client.
"""

__all__ = ["InProcessCluster"]


def __getattr__(name):
    if name == "InProcessCluster":
        from pilosa_tpu.testing.cluster import InProcessCluster

        return InProcessCluster
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
