"""In-process test harness (reference: test/ package, 1000 LoC —
test.MustRunCluster boots n real nodes with real transport on port 0,
test/pilosa.go:344-400)."""

from pilosa_tpu.testing.cluster import InProcessCluster

__all__ = ["InProcessCluster"]
