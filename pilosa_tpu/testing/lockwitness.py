"""Runtime lockdep witness: observe lock acquisition order, trap
inversions live.

The static half (graftlint's ``lock-graph`` pass) proves ordering over
the paths it can resolve; this is the dynamic half, modeled on the Linux
kernel's lockdep *validator*: every ``threading.Lock``/``RLock``
allocated by project code is wrapped so each acquisition records an
edge ``held → acquired`` into a process-global order graph, and the
first acquisition that would create the REVERSE of an already-seen edge
— a two-lock inversion, i.e. a deadlock waiting for the right
interleaving — raises (or logs, configurable) *at the acquisition
site*, with both witness stacks.  Crucially, lockdep-style, the two
orders never have to deadlock to be caught: they only have to both
*happen*, even seconds apart, even on one thread.

Identity: locks are keyed by **allocation site** (file:line of the
``threading.Lock()`` call).  Every instance of a class maps to the same
key — the same per-class granularity the static pass uses for
``(Class, attr)`` fields — so static edges and runtime edges line up
for cross-checking: a static-only edge means a path tests never drive
(suppress it in the pass with the invariant as the reason); a
runtime-only edge means the static resolver missed an alias (fix the
pass).  Locks allocated outside the project scope (stdlib, jax) pass
through unwrapped: zero overhead and no third-party noise.

Semantics matched to real deadlock risk:

* re-acquiring a key already held by this thread records nothing (RLock
  re-entrancy; two same-class instances are indistinguishable by key,
  and same-key nesting is overwhelmingly the re-entrant case);
* non-blocking try-acquires record no edge (a failed/timed attempt
  cannot wait forever) but a SUCCESSFUL one still enters the held set —
  edges from it to later blocking acquisitions are real;
* ``Condition.wait`` releases and re-acquires through the wrapper's
  ``_release_save``/``_acquire_restore`` so the held set stays honest
  across waits.

Enable process-wide with :func:`install` (idempotent), or scoped with
``with lockwitness.active():`` in tests.  tests/conftest.py installs it
for the whole tier-1 run — every already-threaded test doubles as a
race probe — and asserts zero recorded inversions at session end.  Mode
comes from ``PILOSA_LOCKWITNESS`` (``raise`` | ``log`` | ``off``).
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import traceback

logger = logging.getLogger(__name__)

# Real (never-wrapped) primitives, captured at import time so witness
# internals and out-of-scope allocations are untouched.
_real_lock = threading.Lock
_real_rlock = threading.RLock

# Project scope: only locks allocated from files under these path
# fragments are witnessed.
_SCOPE = (f"{os.sep}pilosa_tpu{os.sep}", f"{os.sep}tools{os.sep}",
          f"{os.sep}tests{os.sep}")

# This module's own file plus the stdlib threading module: frames to
# skip when walking for the user-code allocation/acquisition site.
# Exact-path match — a substring test would also skip the witness's own
# test file (tests/test_lockwitness.py).
_SKIP_FILES = (os.path.abspath(__file__), threading.__file__)


class LockOrderInversion(Exception):
    """Two locks were acquired in both orders (potential deadlock)."""


class _State:
    """Process-global witness state (reset by tests)."""

    def __init__(self):
        self.guard = _real_lock()
        # (a, b) -> short witness string for the first observed a-then-b
        self.edges: dict[tuple[str, str], str] = {}
        self.inversions: list[dict] = []
        self.inverted_pairs: set[frozenset] = set()
        self.mode = "off"
        self.installed = False
        self.acquires = 0  # observability: witnessed acquisitions
        self.tls = threading.local()

    def held(self) -> list:
        h = getattr(self.tls, "held", None)
        if h is None:
            h = self.tls.held = []
        return h


_state = _State()


def _alloc_site() -> str | None:
    """file:line of the project frame allocating the lock; None when the
    allocation is out of scope (stdlib/third-party)."""
    f = sys._getframe(2)
    while f is not None and f.f_code.co_filename in _SKIP_FILES:
        f = f.f_back
    if f is None:
        return None
    fn = f.f_code.co_filename
    if not any(s in fn for s in _SCOPE):
        return None
    # repo-relative, stable across checkouts
    for marker in ("pilosa_tpu", "tools", "tests"):
        idx = fn.find(f"{os.sep}{marker}{os.sep}")
        if idx >= 0:
            fn = fn[idx + 1:].replace(os.sep, "/")
            break
    return f"{fn}:{f.f_lineno}"


def _acquire_site() -> str:
    f = sys._getframe(2)
    while f is not None and f.f_code.co_filename in _SKIP_FILES:
        f = f.f_back
    if f is None:  # pragma: no cover - only if called from module top
        return "?"
    fn = f.f_code.co_filename
    for marker in ("pilosa_tpu", "tools", "tests"):
        idx = fn.find(f"{os.sep}{marker}{os.sep}")
        if idx >= 0:
            fn = fn[idx + 1:].replace(os.sep, "/")
            break
    return f"{fn}:{f.f_lineno}"


def _note_acquired(key: str, blocking: bool) -> None:
    st = _state
    held = st.held()
    if any(k == key for k, _site in held):
        held.append((key, None))  # re-entrant depth marker; no edges
        return
    site = _acquire_site()
    st.acquires += 1
    if blocking and held:
        new_edges = []
        inversion = None
        with st.guard:
            for hkey, hsite in held:
                if hsite is None or hkey == key:
                    continue
                edge = (hkey, key)
                if edge not in st.edges:
                    new_edges.append((edge, f"{hsite} then {site}"))
                rev = (key, hkey)
                if rev in st.edges and frozenset(edge) not in st.inverted_pairs:
                    inversion = {
                        "locks": (hkey, key),
                        "thread": threading.current_thread().name,
                        "this_order": f"{hsite} then {site}",
                        "prior_order": st.edges[rev],
                        "stack": "".join(traceback.format_stack(limit=12)),
                    }
                    st.inverted_pairs.add(frozenset(edge))
                    st.inversions.append(inversion)
            for edge, witness in new_edges:
                st.edges[edge] = witness
        if inversion is not None:
            msg = (
                "lock order inversion: "
                f"{inversion['locks'][0]} <-> {inversion['locks'][1]} — "
                f"this thread ({inversion['thread']}): "
                f"{inversion['this_order']}; prior order: "
                f"{inversion['prior_order']}"
            )
            if st.mode == "raise":
                raise LockOrderInversion(msg)
            logger.error("%s\n%s", msg, inversion["stack"])
    held.append((key, site))


def _note_released(key: str) -> None:
    held = _state.held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] == key:
            del held[i]
            return


class _WitnessBase:
    """Wrapper delegating to a real lock, recording order."""

    __slots__ = ("_inner", "_key")

    def __init__(self, inner, key):
        self._inner = inner
        self._key = key

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            try:
                # timeout-bounded acquisitions still count as blocking
                # intent: a thread CAN wait on them, which is what an
                # order edge models
                _note_acquired(self._key, blocking)
            except LockOrderInversion:
                # raise-mode trap: hand the lock back so the caller's
                # with-body never runs half-locked and peers can't hang
                self._inner.release()
                raise
        return ok

    def release(self):
        self._inner.release()
        _note_released(self._key)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def __repr__(self):  # pragma: no cover - debug aid
        return f"<witness {self._key} of {self._inner!r}>"


class _WitnessLock(_WitnessBase):
    pass


class _WitnessRLock(_WitnessBase):
    """RLock wrapper: Condition integration needs the _release_save /
    _acquire_restore / _is_owned trio to route through the witness so
    the held set stays honest across ``wait()``."""

    __slots__ = ()

    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        state = self._inner._release_save()
        _note_released(self._key)
        return state

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)
        _note_acquired(self._key, blocking=True)

    def _at_fork_reinit(self):  # pragma: no cover - fork safety passthrough
        self._inner._at_fork_reinit()


def _make_lock():
    inner = _real_lock()
    if _state.mode == "off":
        return inner
    key = _alloc_site()
    if key is None:
        return inner
    return _WitnessLock(inner, key)


def _make_rlock():
    inner = _real_rlock()
    if _state.mode == "off":
        return inner
    key = _alloc_site()
    if key is None:
        return inner
    return _WitnessRLock(inner, key)


# -- public API --------------------------------------------------------------


def install(mode: str | None = None) -> None:
    """Patch ``threading.Lock``/``RLock`` so project-allocated locks are
    witnessed.  ``mode``: ``raise`` (first inversion raises at the
    acquisition site), ``log`` (recorded + logged, execution continues),
    or ``off``; default from ``PILOSA_LOCKWITNESS`` (falling back to
    ``raise``).  Idempotent; wraps only locks allocated AFTER install.
    """
    if mode is None:
        mode = os.environ.get("PILOSA_LOCKWITNESS", "raise")
    if mode not in ("raise", "log", "off"):
        raise ValueError(f"unknown lockwitness mode {mode!r}")
    _state.mode = mode
    if mode == "off" or _state.installed:
        return
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    _state.installed = True


def uninstall() -> None:
    """Restore the real primitives.  Locks already wrapped keep working
    (their inner lock is real); they just stop being good witnesses once
    their peers are unwrapped."""
    threading.Lock = _real_lock
    threading.RLock = _real_rlock
    _state.installed = False
    _state.mode = "off"


class active:
    """``with lockwitness.active(mode="raise"):`` scoped install for
    tests; resets recorded state on entry, restores the previous
    install state (and clears the scope's recordings) on exit — safe
    inside a session conftest already runs under the witness."""

    def __init__(self, mode: str = "raise"):
        self.mode = mode
        self._prev: tuple[bool, str] | None = None

    def __enter__(self):
        self._prev = (_state.installed, _state.mode)
        reset()
        install(self.mode)
        return self

    def __exit__(self, *exc):
        installed, mode = self._prev
        if installed:
            _state.mode = mode
        else:
            uninstall()
        reset()
        return False


def findings() -> list[dict]:
    """Inversions recorded so far (log mode records without raising;
    raise mode records before raising, so a swallowed exception in a
    worker thread still shows up here)."""
    with _state.guard:
        return list(_state.inversions)


def order_graph() -> dict:
    """{(a, b): witness} — the live acquisition-order edges, for
    cross-checking against the static lock-graph pass."""
    with _state.guard:
        return dict(_state.edges)


def stats() -> dict:
    with _state.guard:
        return {
            "mode": _state.mode,
            "installed": _state.installed,
            "witnessedAcquires": _state.acquires,
            "edges": len(_state.edges),
            "inversions": len(_state.inversions),
        }


def reset() -> None:
    """Clear recorded edges/inversions (NOT the install state)."""
    with _state.guard:
        _state.edges.clear()
        _state.inversions.clear()
        _state.inverted_pairs.clear()
        _state.acquires = 0
