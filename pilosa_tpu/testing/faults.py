"""Deterministic fault injection for chaos tests.

The reference exercises failure handling with container-level tooling
(pumba pause in internal/clustertests) — coarse, slow, and
whole-process.  This registry injects faults at the two I/O boundaries
where partial failure actually manifests, so chaos scenarios become
ordinary reproducible pytest cases:

* the internal client's connection pool (``cluster/client.py``):
  ``reset`` (connection reset before the request is sent), ``slow``
  (a peer that stalls until the caller's socket timeout fires), and
  ``error`` (a synthetic HTTP error response);
* the fragment store's write path (``storage/fragmentfile.py``):
  ``disk_write_fail`` (an OSError from the op-log append or snapshot
  rewrite).

Rules match by fnmatch pattern — peer netloc (``127.0.0.1:9101``) and
request route for network faults, file path for disk faults — and fire
``times`` times (None = unlimited) with probability ``p`` drawn from
the registry's SEEDED RNG, so a probabilistic chaos run replays
identically under the same seed.

Hook points are module-level functions (``network_fault``,
``disk_write_fault``) that cost one global read when no registry is
installed — the production hot path pays nothing.
"""

from __future__ import annotations

import fnmatch
import random
import threading
import time

KINDS_NETWORK = ("reset", "slow", "error")
KINDS_DISK = ("disk_write_fail",)
# "crash" fires at named protocol stages (resize/migration phase
# boundaries call ``stage_fault("coordinator:flip")`` etc.) and raises
# CrashError there — a surgical stand-in for killing that participant
# at exactly that point in the protocol.
KINDS_STAGE = ("crash",)
KINDS = KINDS_NETWORK + KINDS_DISK + KINDS_STAGE


class CrashError(RuntimeError):
    """Raised by a fired ``crash`` rule: the participant 'dies' at this
    protocol stage (the surrounding code must treat it like any other
    unexpected failure)."""


class Fault:
    """One injection rule; mutate ``times``/inspect ``hits`` freely."""

    def __init__(
        self,
        kind: str,
        peer: str | None = None,
        route: str | None = None,
        path: str | None = None,
        stage: str | None = None,
        delay: float = 0.0,
        code: int = 503,
        times: int | None = None,
        p: float = 1.0,
    ):
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} (one of {KINDS})")
        self.kind = kind
        self.peer = peer      # fnmatch on netloc, e.g. "127.0.0.1:91*"
        self.route = route    # fnmatch on request path, e.g. "/index/*"
        self.path = path      # fnmatch on file path (disk faults)
        self.stage = stage    # fnmatch on stage name (crash faults)
        self.delay = float(delay)
        self.code = int(code)
        self.times = times    # remaining firings; None = unlimited
        self.p = float(p)
        self.hits = 0         # observability: how often this rule fired

    def matches_network(self, netloc: str, route: str) -> bool:
        if self.kind not in KINDS_NETWORK:
            return False
        if self.peer is not None and not fnmatch.fnmatch(netloc, self.peer):
            return False
        if self.route is not None and not fnmatch.fnmatch(route, self.route):
            return False
        return True

    def matches_disk(self, path: str) -> bool:
        if self.kind not in KINDS_DISK:
            return False
        return self.path is None or fnmatch.fnmatch(path, self.path)

    def matches_stage(self, stage: str) -> bool:
        if self.kind not in KINDS_STAGE:
            return False
        return self.stage is None or fnmatch.fnmatch(stage, self.stage)


class FaultRegistry:
    """Thread-safe rule set with a seeded RNG for probabilistic rules."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._faults: list[Fault] = []
        # Observer called OUTSIDE the registry lock after a rule fires:
        # fn(kind, target) — the test cluster wires this into the event
        # journal so injected faults appear on the cluster timeline.
        self.on_fire = None

    def add(self, kind: str, **kw) -> Fault:
        fault = Fault(kind, **kw)
        with self._lock:
            self._faults.append(fault)
        return fault

    def remove(self, fault: Fault) -> None:
        with self._lock:
            if fault in self._faults:
                self._faults.remove(fault)

    def clear(self) -> None:
        with self._lock:
            self._faults.clear()

    def _fire(self, fault: Fault) -> bool:
        """Consume one firing of a matched rule (lock held by caller)."""
        if fault.times is not None and fault.times <= 0:
            return False
        if fault.p < 1.0 and self._rng.random() >= fault.p:
            return False
        if fault.times is not None:
            fault.times -= 1
        fault.hits += 1
        return True

    # -- hook implementations ----------------------------------------------

    def network_fault(
        self, netloc: str, route: str, timeout: float | None
    ) -> tuple[int, bytes, str] | None:
        """Apply the first matching network rule.

        ``reset`` raises ConnectionResetError; ``slow`` emulates a
        stalled peer faithfully — the caller blocks for
        ``min(delay, socket timeout)`` and gets TimeoutError if the
        stall outlives its timeout; ``error`` short-circuits with a
        synthetic ``(status, body, content-type)`` response."""
        with self._lock:
            fired = None
            for fault in self._faults:
                if fault.matches_network(netloc, route) and self._fire(fault):
                    fired = fault
                    break
        if fired is None:
            return None
        self._notify(fired, f"{netloc}{route}")
        if fired.kind == "reset":
            raise ConnectionResetError(
                f"fault-injected connection reset ({netloc}{route})"
            )
        if fired.kind == "slow":
            stall = fired.delay
            if timeout is not None and timeout >= 0:
                stall = min(stall, timeout)
            time.sleep(stall)
            if timeout is not None and fired.delay > timeout:
                raise TimeoutError(
                    f"fault-injected slow peer ({netloc}{route}): "
                    f"stalled past the {timeout:.3f}s socket timeout"
                )
            return None  # delay fit in the timeout; request proceeds
        # error
        body = (
            '{"error": "fault-injected error %d"}' % fired.code
        ).encode()
        return fired.code, body, "application/json"

    def disk_write_fault(self, path: str) -> None:
        with self._lock:
            fired = None
            for fault in self._faults:
                if fault.matches_disk(path) and self._fire(fault):
                    fired = fault
                    break
        if fired is not None:
            self._notify(fired, path)
            raise OSError(f"fault-injected disk write failure: {path}")

    def stage_fault(self, stage: str) -> None:
        """Crash the caller at a named protocol stage.  Stage names are
        ``<role>:<phase>`` (e.g. ``coordinator:flip``, ``source:chunk``,
        ``target:apply``); rules fnmatch against them."""
        with self._lock:
            fired = None
            for fault in self._faults:
                if fault.matches_stage(stage) and self._fire(fault):
                    fired = fault
                    break
        if fired is not None:
            self._notify(fired, stage)
            raise CrashError(f"fault-injected crash at stage: {stage}")

    def _notify(self, fault: Fault, target: str) -> None:
        """Invoke the observer (no lock held); observer bugs never mask
        the fault being injected."""
        cb = self.on_fire
        if cb is None:
            return
        try:
            cb(fault.kind, target)
        except Exception:  # graftlint: disable=exception-hygiene -- observer is best-effort; a journal bug must not mask the injected fault
            pass


# -- global hook points ------------------------------------------------------

_active: FaultRegistry | None = None


def install(registry: FaultRegistry) -> FaultRegistry:
    global _active
    _active = registry
    return registry


def uninstall(registry: FaultRegistry | None = None) -> None:
    """Remove the active registry (or only ``registry`` if given and
    active — lets overlapping harnesses not clobber each other)."""
    global _active
    if registry is None or _active is registry:
        _active = None


def active() -> FaultRegistry | None:
    return _active


def network_fault(
    netloc: str, route: str, timeout: float | None
) -> tuple[int, bytes, str] | None:
    """Hook point: called by the internal client's pool per request."""
    registry = _active
    if registry is None:
        return None
    return registry.network_fault(netloc, route, timeout)


def disk_write_fault(path: str) -> None:
    """Hook point: called by FragmentFile before op-log/snapshot writes."""
    registry = _active
    if registry is not None:
        registry.disk_write_fault(path)


def stage_fault(stage: str) -> None:
    """Hook point: called at resize/migration protocol stage boundaries."""
    registry = _active
    if registry is not None:
        registry.stage_fault(stage)
