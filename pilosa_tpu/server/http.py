"""HTTP transport (reference: http/handler.go, 1702 LoC).

Route surface mirrors the reference's public router (handler.go:276-314):

    GET  /                               -> redirect note
    GET  /version /status /info /schema
    POST /schema
    POST /index/{index}                  create index
    GET  /index/{index}
    DELETE /index/{index}
    POST /index/{index}/query            PQL body -> {"results": [...]}
    POST /index/{index}/field/{field}    create field
    GET/DELETE /index/{index}/field/{field}
    POST /index/{index}/field/{field}/import           JSON batch
    POST /index/{index}/field/{field}/import-roaring/{shard}  binary roaring
    GET  /export?index=&field=           CSV
    GET  /internal/shards/max
    POST /internal/translate/keys

JSON replaces the reference's protobuf codec (encoding/proto) as this
framework's wire format; the roaring import payload is binary-compatible
with reference clients. Long-running queries log at a threshold like the
reference's long-query-time (handler.go:246-248).
"""

from __future__ import annotations

import gzip as gzip_mod
import json
import logging
import math
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from pilosa_tpu import deadline
from pilosa_tpu.deadline import DeadlineExceeded
from pilosa_tpu.obs import devledger, slo, tracestore, tracing
from pilosa_tpu.server.api import API, ApiError
from pilosa_tpu.server.qos import ShedError

logger = logging.getLogger(__name__)

# SLO op class by route, for routes whose class is knowable from the
# path alone; query routes are classified by the API layer (it has the
# parsed call tree) via slo.note_class, which takes precedence.
_SLO_ROUTE_CLASS = {
    "query": slo.OP_READ_OTHER,
    "import_": slo.OP_IMPORT,
    "import_roaring": slo.OP_IMPORT,
    "translate_keys": slo.OP_TRANSLATE,
    "translate_ids": slo.OP_TRANSLATE,
}

# GET /debug discoverability index: every registered debug surface with
# a one-line description (there are 10+ — nobody remembers them all).
_DEBUG_ENDPOINTS: list[tuple[str, str]] = [
    ("/debug/vars",
     "expvar-style dump: counters, histograms, kernels, device budget"),
    ("/debug/history",
     "ring-buffer metrics history (?series=glob&since=&step=&cluster=true)"),
    ("/debug/slo",
     "per-op-class latency quantiles, error budgets, burn-rate alerts"),
    ("/debug/qos",
     "cost-governed admission: per-tenant queues, shed/degrade ladder"),
    ("/debug/events",
     "typed cluster event journal (?since= cursor, ?cluster=true merge)"),
    ("/debug/traces",
     "tail-sampled trace store (?id= spans, ?cluster=true assembly)"),
    ("/debug/incidents",
     "flight-recorder bundles: alert edges, 504 spikes, trend incidents"),
    ("/debug/postmortem",
     "sealed crash bundles from the black box (?id=, ?cluster=true merge)"),
    ("/debug/devcosts",
     "device cost ledger: compiles/launches/transfers per site+tenant"),
    ("/debug/slow-queries",
     "bounded worst-offender log with full execution profiles"),
    ("/debug/jobs",
     "background-job progress: resize, anti-entropy, import drains"),
    ("/debug/fragments",
     "per-fragment container stats, op-log length, device residency"),
    ("/debug/threads", "per-thread stack dump"),
    ("/debug/profile",
     "sampled CPU profile, flamegraph-collapsed (?seconds=&interval_ms=)"),
    ("/debug/memory", "RSS, host mirror bytes, HBM budget, GC state"),
]

_ROUTES: list[tuple[str, re.Pattern, str]] = [
    ("GET", re.compile(r"^/$"), "root"),
    ("GET", re.compile(r"^/version$"), "version"),
    ("GET", re.compile(r"^/status$"), "status"),
    ("GET", re.compile(r"^/info$"), "info"),
    ("GET", re.compile(r"^/schema$"), "get_schema"),
    ("POST", re.compile(r"^/schema$"), "post_schema"),
    ("GET", re.compile(r"^/metrics$"), "metrics"),
    ("GET", re.compile(r"^/debug$"), "debug_index"),
    ("GET", re.compile(r"^/debug/vars$"), "debug_vars"),
    ("GET", re.compile(r"^/debug/history$"), "debug_history"),
    ("GET", re.compile(r"^/debug/slo$"), "debug_slo"),
    ("GET", re.compile(r"^/debug/qos$"), "debug_qos"),
    ("GET", re.compile(r"^/debug/slow-queries$"), "debug_slow_queries"),
    ("GET", re.compile(r"^/debug/threads$"), "debug_threads"),
    ("GET", re.compile(r"^/debug/profile$"), "debug_profile"),
    ("GET", re.compile(r"^/debug/memory$"), "debug_memory"),
    ("GET", re.compile(r"^/debug/events$"), "debug_events"),
    ("GET", re.compile(r"^/debug/traces$"), "debug_traces"),
    ("GET", re.compile(r"^/debug/incidents$"), "debug_incidents"),
    ("GET", re.compile(r"^/debug/postmortem$"), "debug_postmortem"),
    ("GET", re.compile(r"^/debug/devcosts$"), "debug_devcosts"),
    ("GET", re.compile(r"^/debug/jobs$"), "debug_jobs"),
    ("GET", re.compile(r"^/debug/fragments$"), "debug_fragments"),
    ("GET", re.compile(r"^/internal/diagnostics$"), "diagnostics"),  # graftlint: disable=dispatch-parity -- operator debug endpoint (curl/monitoring), never called node-to-node
    ("GET", re.compile(r"^/export$"), "export"),
    ("POST", re.compile(r"^/index/(?P<index>[^/]+)/query$"), "query"),
    ("POST", re.compile(r"^/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)/import$"), "import_"),
    ("POST", re.compile(r"^/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)/import-roaring/(?P<shard>\d+)$"), "import_roaring"),
    ("POST", re.compile(r"^/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)$"), "create_field"),
    ("GET", re.compile(r"^/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)$"), "get_field"),
    ("DELETE", re.compile(r"^/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)$"), "delete_field"),
    ("POST", re.compile(r"^/index/(?P<index>[^/]+)$"), "create_index"),
    ("GET", re.compile(r"^/index/(?P<index>[^/]+)$"), "get_index"),
    ("DELETE", re.compile(r"^/index/(?P<index>[^/]+)$"), "delete_index"),
    ("GET", re.compile(r"^/internal/shards/max$"), "shards_max"),
    ("POST", re.compile(r"^/internal/translate/keys$"), "translate_keys"),
    ("POST", re.compile(r"^/internal/translate/ids$"), "translate_ids"),
    ("GET", re.compile(r"^/internal/translate/log$"), "translate_log"),
    ("POST", re.compile(r"^/internal/translate/restore$"), "translate_restore"),
    ("POST", re.compile(r"^/cluster/resize/set-coordinator$"), "set_coordinator"),
    ("POST", re.compile(r"^/cluster/resize/abort$"), "resize_abort"),
    ("POST", re.compile(r"^/cluster/resize/remove-node$"), "remove_node"),
    ("POST", re.compile(r"^/recalculate-caches$"), "recalculate_caches"),
    ("POST", re.compile(r"^/internal/cluster/message$"), "cluster_message"),
    ("GET", re.compile(r"^/internal/attr/blocks$"), "attr_blocks"),
    ("POST", re.compile(r"^/internal/attr/block/data$"), "attr_block_data"),
    ("GET", re.compile(r"^/internal/fragment/blocks$"), "fragment_blocks"),
    ("POST", re.compile(r"^/internal/fragment/block/data$"), "fragment_block_data"),
    ("GET", re.compile(r"^/internal/fragment/data$"), "fragment_data"),
    ("GET", re.compile(r"^/internal/fragments$"), "fragments"),
    ("POST", re.compile(r"^/internal/resize/fetch$"), "resize_fetch"),
    ("POST", re.compile(r"^/internal/migrate/begin$"), "migrate_begin"),
    ("GET", re.compile(r"^/internal/migrate/chunk$"), "migrate_chunk"),
    ("POST", re.compile(r"^/internal/migrate/delta$"), "migrate_delta"),
    ("POST", re.compile(r"^/internal/migrate/end$"), "migrate_end"),
    ("POST", re.compile(r"^/internal/migrate/fetch$"), "migrate_fetch"),
    ("POST", re.compile(r"^/internal/migrate/finalize$"), "migrate_finalize"),
    ("POST", re.compile(r"^/cluster/resize/resume$"), "resize_resume"),
    ("GET", re.compile(r"^/internal/nodes$"), "nodes"),
]


class Handler(BaseHTTPRequestHandler):
    api: API = None  # set by make_server
    long_query_time: float = 0.0
    default_deadline: float = 0.0  # seconds; 0 = no default deadline
    protocol_version = "HTTP/1.1"
    # TCP_NODELAY on accepted sockets (socketserver applies this in
    # StreamRequestHandler.setup): with keep-alive connections (the
    # pooled internal client), Nagle + the peer's delayed ACK would add
    # ~40 ms to every small response
    disable_nagle_algorithm = True

    # -- plumbing -----------------------------------------------------------

    def log_message(self, fmt, *args):  # route through logging, not stderr
        logger.debug(fmt, *args)

    # gzip floor: tiny bodies cost more in header + CPU than they save
    _GZIP_MIN_BYTES = 512

    def _send(
        self,
        code: int,
        body: bytes,
        content_type: str = "application/json",
        headers: dict | None = None,
        gzip_ok: bool = False,
    ) -> None:
        if (
            gzip_ok
            and len(body) >= self._GZIP_MIN_BYTES
            and "gzip" in (self.headers.get("Accept-Encoding") or "")
        ):
            body = gzip_mod.compress(body, compresslevel=1)
            headers = dict(headers or {})
            headers["Content-Encoding"] = "gzip"
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_json(
        self,
        code: int,
        obj,
        headers: dict | None = None,
        gzip_ok: bool = False,
    ) -> None:
        self._send(
            code, (json.dumps(obj) + "\n").encode(), headers=headers,
            gzip_ok=gzip_ok,
        )

    def _body(self) -> bytes:
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    def _json_body(self) -> dict:
        raw = self._body()
        if not raw:
            return {}
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise ApiError(f"invalid json: {e}")

    def _request_budget(self) -> float | None:
        """Deadline budget for this request, by precedence: explicit
        ``timeout=`` query param (seconds) > ``X-Pilosa-Deadline`` header
        (remaining budget forwarded by an upstream node) > the server's
        configured default.  None/0 disables the deadline — malformed
        values fall through rather than erroring, matching header
        semantics (a bad deadline must not reject the request)."""
        raw = self.query_params.get("timeout", [None])[0]
        budget = deadline.from_header(raw)
        if budget is None:
            budget = deadline.from_header(self.headers.get(deadline.HEADER))
        if budget is None and self.default_deadline > 0:
            budget = self.default_deadline
        return budget

    def _dispatch(self, method: str) -> None:
        if getattr(type(self), "paused", None) is not None and type(self).paused.is_set():
            # Fault injection: emulate a paused process (reference uses
            # pumba pause in internal/clustertests) — drop the connection
            # without responding so clients see timeouts/resets.
            self.close_connection = True
            try:
                self.connection.close()
            except OSError:
                pass
            return
        parsed = urlparse(self.path)
        self.query_params = parse_qs(parsed.query)
        for m, rx, name in _ROUTES:
            if m != method:
                continue
            match = rx.match(parsed.path)
            if match:
                t0 = time.monotonic()
                # Route this request's spans into THIS node's trace
                # store (contextvar: in-process multi-node clusters share
                # the process-global tracer but not their stores).
                store_token = tracestore._active_store.set(
                    getattr(self.api.holder, "traces", None)
                )
                # Join an incoming cross-node trace, or root a new one
                # (reference http/handler.go extracts opentracing headers).
                parent = tracing.get_tracer().extract_headers(self.headers)
                span = tracing.start_span(f"http.{name}", child_of=parent)
                span.set_tag("method", method).set_tag("path", parsed.path)
                # Error budget: server-attributed failures only.  504s
                # (deadline/batcher expiry) and 500s burn budget; 4xx
                # client mistakes don't.
                slo_error = False
                # span lifecycle is manual (not `with span:`) so the
                # op-class and error verdict — known only after the
                # handler ran — are tagged BEFORE finish(): the tail-
                # sampling decision at root completion reads both.
                span.__enter__()
                try:
                    # Tenant attribution: the device cost ledger books
                    # every launch this request causes under the header's
                    # tenant (canonical "(default)" when untagged); the
                    # contextvar rides into the api/executor layers and
                    # batcher flight snapshots.
                    with devledger.tenant_scope(
                        self.headers.get(devledger.TENANT_HEADER)
                    ), deadline.scope(self._request_budget()):
                        getattr(self, "r_" + name)(**match.groupdict())
                except ShedError as e:
                    # QoS load shed (server/qos.py stage 3): explicit
                    # 429 + Retry-After, NEVER a silent 504 — and a 4xx,
                    # so backpressure does not burn the error budget it
                    # exists to protect.
                    retry = max(1, math.ceil(e.retry_after))
                    self.api.holder.stats.count_with_tags(
                        "http_shed", 1, 1.0, (f"tenant:{e.tenant}",)
                    )
                    self._send_json(
                        429,
                        {"error": str(e), "retryAfter": retry},
                        headers={"Retry-After": str(retry)},
                    )
                except DeadlineExceeded as e:
                    # Distinct from ApiError (400-family): a spent budget
                    # is a timeout, not a client mistake (reference maps
                    # context.DeadlineExceeded similarly).
                    slo_error = True
                    self.api.holder.stats.count(
                        "http_deadline_exceeded", 1, 1.0
                    )
                    self._send_json(504, {"error": f"deadline exceeded: {e}"})
                except ApiError as e:
                    slo_error = e.code >= 500
                    self._send_json(e.code, {"error": str(e)})
                except BrokenPipeError:
                    pass
                except Exception as e:  # internal error
                    slo_error = True
                    logger.exception("internal error")
                    self._send_json(500, {"error": f"internal: {e}"})
                finally:
                    elapsed = time.monotonic() - t0
                    op_class = slo.take_class() or _SLO_ROUTE_CLASS.get(
                        name, slo.OP_OTHER
                    )
                    span.set_tag("op_class", op_class)
                    if slo_error:
                        span.set_tag("error", True)
                    span.__exit__(None, None, None)
                    tracestore._active_store.reset(store_token)
                    # Per-tenant SLO dimension: the request also lands
                    # under "op_class@tenant" (obs/slo.py) so a single
                    # tenant's objective/error budget is trackable —
                    # the QoS ladder's per-victim pressure signal.
                    tenant = devledger.clean_tenant(
                        self.headers.get(devledger.TENANT_HEADER)
                    )
                    self.api.holder.slo.observe(
                        op_class, elapsed, slo_error, tenant=tenant
                    )
                    self.api.holder.stats.count_with_tags(
                        "http_requests", 1, 1.0, (f"route:{name}",)
                    )
                    self.api.holder.stats.timing("http_request", elapsed)
                    if self.long_query_time and elapsed > self.long_query_time:
                        logger.warning(
                            "long query %.3fs: %s %s", elapsed, method, self.path
                        )
                return
        self._send_json(404, {"error": "not found"})

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_DELETE(self):
        self._dispatch("DELETE")

    # -- routes -------------------------------------------------------------

    def r_root(self):
        self._send_json(200, {"message": "pilosa-tpu server. See /schema, /status, /index/{index}/query."})

    def r_version(self):
        self._send_json(200, self.api.version())

    def r_status(self):
        self._send_json(200, self.api.status())

    def r_info(self):
        self._send_json(200, self.api.info())

    def r_get_schema(self):
        self._send_json(200, self.api.schema())

    def r_metrics(self):
        """Prometheus text exposition (reference http/handler.go:282).
        Kernel-dispatch telemetry lives in its own process-global
        registry (ops/kernels.kernel_stats) so it is visible even when
        the holder uses a NopStatsClient; both registries are rendered
        into the one scrape."""
        from pilosa_tpu import __version__
        from pilosa_tpu.core import membudget, residency, translate
        from pilosa_tpu.obs import sysinfo
        from pilosa_tpu.obs.stats import prometheus_text
        from pilosa_tpu.ops import kernels

        # Device-budget occupancy refreshes at scrape time — gauges, not
        # counters, so no background poller is needed.
        stats = self.api.holder.stats
        if hasattr(stats, "gauge"):
            # process self-metrics refresh at scrape time (satellites of
            # the black-box plane: a restarted process is visible as a
            # start-time jump + uptime reset without any poller race)
            info = sysinfo.SystemInfo()
            stats.gauge(
                "process_uptime_seconds", round(info.process_uptime(), 3)
            )
            stats.gauge(
                "process_start_time_seconds", info.process_start_time()
            )
            dev = membudget.default_budget().snapshot()
            stats.gauge("device_used_bytes", dev["usedBytes"])
            stats.gauge("device_cap_bytes", dev["capBytes"] or 0)
            stats.gauge("device_entries", dev["entries"])
            stats.gauge("device_evictions", dev["evictions"])
            # residency tiers: query-path hit/miss, predictive-prefetch
            # yield, and the pin working set (core/residency.py)
            res = residency.default_tracker().snapshot()
            stats.gauge("device_hits", res["deviceHits"])
            stats.gauge("device_misses", res["deviceMisses"])
            stats.gauge("device_prefetch_issued", res["prefetchIssued"])
            stats.gauge("device_prefetch_useful", res["prefetchUseful"])
            stats.gauge("device_pins", dev["pins"])
            stats.gauge("device_pinned_entries", dev["pinnedEntries"])
            stats.gauge("device_pinned_bytes", dev["pinnedBytes"])
        # Kernel + key-translation telemetry live in process-global
        # registries (visible under NopStatsClient holders); the SLO
        # plane renders its own pilosa_slo_* series from the tracker.
        # Histogram buckets expose OpenMetrics exemplars filtered to
        # traces the tail sampler actually kept, so every exemplar id
        # resolves at /debug/traces?id=.
        kept = self.api.holder.traces.kept_ids()
        filt = kept.__contains__
        text = (
            prometheus_text(self.api.holder.stats, exemplar_filter=filt)
            + prometheus_text(kernels.kernel_stats, exemplar_filter=filt)
            + prometheus_text(translate.translate_stats)
            + self.api.holder.slo.prometheus_text(exemplar_filter=filt)
            + devledger.prometheus_text()
            + sysinfo.build_info_text(__version__)
        )
        self._send(
            200,
            text.encode(),
            content_type="text/plain; version=0.0.4",
            gzip_ok=True,
        )

    def r_debug_vars(self):
        """expvar-style dump (reference http/handler.go:281), including
        the executor's serving-cache counters (the analogue of the
        reference's cache stats, cache.go/stats)."""
        stats = self.api.holder.stats
        snap = dict(stats.snapshot()) if hasattr(stats, "snapshot") else {}
        ex = getattr(self.api, "executor", None)
        if ex is not None:
            snap["serving_cache"] = {
                "gram_hits": ex.gram_cache_hits,
                "rowcount_hits": ex.rowcount_cache_hits,
                "crossgram_hits": ex.crossgram_cache_hits,
                "bsi_agg_hits": ex.bsi_agg_cache_hits,
                "stack_rebuilds": ex.stack_rebuilds,
                "stack_incremental": ex.stack_incremental,
                "bsi_stack_launches": ex.bsi_stack_launches,
            }
            # semantic result cache: hit/miss/invalidation counters plus
            # promotion state of the maintained TopN/GroupBy views
            # (exec/rescache.py)
            snap["rescache"] = ex.rescache.snapshot()
            # flight planner: CSE sharing, reorder, and measured lane
            # decisions, plus both lanes' live price list
            # (exec/planner.py)
            snap["planner"] = ex.planner.snapshot()
        from pilosa_tpu.core import membudget, residency, translate
        from pilosa_tpu.ops import kernels

        snap["kernels"] = kernels.telemetry_snapshot()
        snap["device"] = membudget.default_budget().snapshot()
        # residency-tier counters: hit/miss rates, prefetch yield, pin
        # policy outcomes (core/residency.py)
        snap["residency"] = residency.default_tracker().snapshot()
        snap["devledger"] = devledger.snapshot()
        snap["events"] = self.api.holder.events.snapshot_summary()
        snap["slo"] = self.api.holder.slo.summary()
        snap["translate"] = translate.telemetry_snapshot()
        batcher = getattr(self.api, "batcher", None)
        if batcher is not None:
            # serving-plane block: queue depth, window knobs, flights
            snap["batcher"] = batcher.snapshot()
        if getattr(self.api, "qos", None) is not None:
            # cost-governed admission: per-tenant WFQ + ladder stages
            snap["qos"] = self.api.qos_snapshot()
        ingest = getattr(self.api, "ingest", None)
        if ingest is not None:
            # ingest-plane block: pool depth/inflight, staging occupancy,
            # upload overlap — the pipeline's live tuning signals
            snap["ingest"] = ingest.snapshot()
        migrations = getattr(self.api, "migrations", None)
        if migrations is not None:
            # source-side migration sessions: per-fragment pending
            # delta ops = live catch-up lag during an online resize
            snap["migrations"] = migrations.snapshot_summary()
        dist = getattr(self.api, "dist", None)
        if dist is not None:
            # cluster-on-mesh routing: the placement map plus recent
            # per-call partition decisions (mesh vs HTTP vs local)
            snap["dist"] = dist.snapshot()
        from pilosa_tpu import __version__
        from pilosa_tpu.obs import sysinfo

        # process identity block: pid/version/uptime — distinct from the
        # host report in /info (sysinfo.py reports host uptime there)
        snap["process"] = sysinfo.SystemInfo().process_block(__version__)
        blackbox = getattr(self.api, "blackbox", None)
        if blackbox is not None:
            # black-box writer self-accounting: checkpoint counts/cost,
            # spool size, crash-loop state (obs/blackbox.py)
            snap["blackbox"] = blackbox.stats()
        self._send_json(200, snap)

    def r_debug_slo(self):
        """Live SLO state: per-op-class latency quantiles, windowed
        availability, burn rates, alert firing, pass/fail verdicts."""
        self._send_json(200, self.api.slo_snapshot())

    def r_debug_qos(self):
        """Cost-governed admission state: per-tenant weighted-fair
        queues (debt, cost estimate, effective weight), pressure-ladder
        stages, shed/degraded counters and recent transitions
        (server/qos.py)."""
        self._send_json(200, self.api.qos_snapshot())

    def r_debug_index(self):
        """Debug-surface directory: every /debug/* endpoint with a
        one-line description."""
        self._send_json(200, {
            "endpoints": [
                {"path": p, "desc": d} for p, d in _DEBUG_ENDPOINTS
            ],
        })

    def r_debug_history(self):
        """Ring-buffer metrics history (obs/history.py): ?series= glob
        filter, ?since= base-seq cursor (gap-honest `truncated` flag),
        ?step= downsampling (tier selection + mean buckets),
        ?cluster=true merges every peer's series into one wall-clock-
        aligned timeline with per-node attribution."""
        series = self.query_params.get("series", [None])[0]
        try:
            since_raw = self.query_params.get("since", [None])[0]
            since = int(since_raw) if since_raw is not None else None
            step_raw = self.query_params.get("step", [None])[0]
            step = float(step_raw) if step_raw is not None else None
            limit_raw = self.query_params.get("limit", [None])[0]
            limit = int(limit_raw) if limit_raw is not None else None
        except ValueError:
            self._send_json(400, {"error": "bad since/step/limit"})
            return
        if self.query_params.get("cluster", ["false"])[0].lower() in (
            "1", "true", "yes",
        ):
            self._send_json(
                200, self.api.cluster_history(series=series, step=step),
                gzip_ok=True,
            )
            return
        snap = self.api.history_query(
            series=series, since=since, step=step, limit=limit
        )
        if snap is None:
            self._send_json(404, {"error": "metrics history disabled"})
            return
        self._send_json(200, snap, gzip_ok=True)

    def r_debug_events(self):
        """Event journal past ?since=<seq> (gap-free cursor resume);
        ?cluster=true fans out to every peer and merges the journals
        into one cluster timeline."""
        try:
            since = int(self.query_params.get("since", ["0"])[0])
            limit_raw = self.query_params.get("limit", [None])[0]
            limit = int(limit_raw) if limit_raw is not None else None
        except ValueError:
            self._send_json(400, {"error": "bad since/limit"})
            return
        if self.query_params.get("cluster", ["false"])[0].lower() in (
            "1", "true", "yes",
        ):
            self._send_json(200, self.api.cluster_events(since))
            return
        self._send_json(200, self.api.events_since(since, limit))

    def r_debug_traces(self):
        """Tail-sampled trace store: kept-trace list, ?id=<32hex> span
        detail, ?cluster=true coordinator fan-out (with id: assemble one
        trace's spans from every node; without: merge kept summaries)."""
        trace_id = self.query_params.get("id", [None])[0]
        try:
            limit = int(self.query_params.get("limit", ["100"])[0])
        except ValueError:
            self._send_json(400, {"error": "bad limit"})
            return
        if self.query_params.get("cluster", ["false"])[0].lower() in (
            "1", "true", "yes",
        ):
            if trace_id:
                self._send_json(
                    200, self.api.cluster_trace(trace_id), gzip_ok=True
                )
            else:
                self._send_json(
                    200, self.api.cluster_traces(limit), gzip_ok=True
                )
            return
        if trace_id:
            if self.query_params.get("spans", ["false"])[0].lower() in (
                "1", "true", "yes",
            ):
                # peer leg of cluster assembly: raw local spans, kept
                # OR recent, 200 even when empty
                self._send_json(
                    200, self.api.trace_spans(trace_id), gzip_ok=True
                )
                return
            detail = self.api.trace_detail(trace_id)
            if detail is None:
                self._send_json(404, {"error": f"trace {trace_id} not kept"})
            else:
                self._send_json(200, detail, gzip_ok=True)
            return
        self._send_json(200, self.api.traces_snapshot(limit), gzip_ok=True)

    def r_debug_incidents(self):
        """Flight-recorder incident bundles (alert-edge / 504-spike
        auto-captures): list, or full bundle with ?id=."""
        incident_id = self.query_params.get("id", [None])[0]
        if incident_id:
            detail = self.api.incident_detail(incident_id)
            if detail is None:
                self._send_json(
                    404, {"error": f"incident {incident_id} not found"}
                )
            else:
                self._send_json(200, detail)
            return
        self._send_json(200, self.api.incidents_snapshot())

    def r_debug_postmortem(self):
        """Sealed crash bundles from the black box (obs/blackbox.py):
        bare GET returns retained summaries + the newest bundle in
        full; ?id= one bundle; ?cluster=true merges every peer's
        summaries at the coordinator."""
        if self.query_params.get("cluster", ["false"])[0].lower() in (
            "1", "true", "yes",
        ):
            self._send_json(
                200, self.api.cluster_postmortems(), gzip_ok=True
            )
            return
        pm_id = self.query_params.get("id", [None])[0]
        snap = self.api.postmortem_snapshot(pm_id)
        if snap is None:
            if pm_id:
                self._send_json(
                    404, {"error": f"postmortem {pm_id} not found"}
                )
            else:
                self._send_json(
                    404, {"error": "black box disabled (no data dir)"}
                )
            return
        self._send_json(200, snap, gzip_ok=True)

    def r_debug_devcosts(self):
        """Device cost ledger: per-site and per-(tenant, index, op_class)
        compile/launch/transfer accounting with rates, plus recompile-
        storm state (obs/devledger.py)."""
        self._send_json(200, devledger.snapshot())

    def r_debug_jobs(self):
        """Background-job records: active + bounded history, with phase,
        progress counters, rates and ETA (?kind= filters)."""
        kind = self.query_params.get("kind", [None])[0]
        self._send_json(200, self.api.jobs_snapshot(kind))

    def r_debug_fragments(self):
        """Per-fragment storage/residency introspection
        (?index=&field= filter)."""
        index = self.query_params.get("index", [None])[0]
        field = self.query_params.get("field", [None])[0]
        self._send_json(200, self.api.fragment_details(index, field))

    def r_debug_slow_queries(self):
        """Bounded worst-offender log of queries over the server's
        slow-query threshold (reference's long-query-time logging,
        handler.go:246-248, upgraded to a structured endpoint: each
        entry keeps the full execution profile of the offending
        query)."""
        self._send_json(200, self.api.slow_queries.snapshot())

    def r_debug_threads(self):
        """Per-thread stack dump — the pprof goroutine-profile analogue
        (reference mounts net/http/pprof, http/handler.go:280)."""
        import sys
        import traceback

        frames = sys._current_frames()
        out = []
        for t in threading.enumerate():
            frame = frames.get(t.ident)
            out.append(
                {
                    "name": t.name,
                    "daemon": t.daemon,
                    "stack": traceback.format_stack(frame) if frame else [],
                }
            )
        self._send_json(200, {"threads": out, "count": len(out)})

    def r_debug_profile(self):
        """CPU sampling profile of every thread for ?seconds=N (cap 30);
        flamegraph-collapsed stacks — the net/http/pprof profile-
        endpoint role (reference http/handler.go:280).  The request
        thread does the sampling; the threaded server keeps serving."""
        import math

        from pilosa_tpu.obs import profile

        try:
            seconds = float(self.query_params.get("seconds", ["2"])[0])
            interval = (
                float(self.query_params.get("interval_ms", ["5"])[0]) / 1e3
            )
            if not (math.isfinite(seconds) and math.isfinite(interval)):
                raise ValueError
            if seconds <= 0 or interval <= 0:
                raise ValueError
        except ValueError:
            self._send_json(400, {"error": "bad seconds/interval_ms"})
            return
        # clamp BOTH ways: a huge interval would park this server thread
        # in time.sleep far past the seconds cap
        interval = min(max(0.001, interval), 1.0)
        # The sampler blocks this request thread for the whole window:
        # cap it by the caller's remaining deadline budget (at 90%, so
        # serialization still fits) instead of sampling into a 504.
        deadline.check("debug/profile")
        rem = deadline.remaining()
        if rem is not None:
            seconds = min(seconds, max(0.05, rem * 0.9))
        self._send_json(200, profile.sample(seconds, interval))

    def r_debug_memory(self):
        """Heap/memory snapshot: RSS, host mirror bytes by index, HBM
        budget accounting, GC state — the pprof heap-profile role
        shaped to this runtime's actual memory owners."""
        from pilosa_tpu.obs import profile

        self._send_json(200, profile.memory_snapshot(self.api.holder))

    def r_diagnostics(self):
        """Diagnostics snapshot (reference diagnostics.go payload; local
        endpoint replaces the reference's phone-home POST)."""
        diag = getattr(self.api, "diagnostics", None)
        if diag is None:
            self._send_json(404, {"error": "diagnostics not enabled"})
            return
        self._send_json(200, diag.snapshot())

    def r_post_schema(self):
        self.api.apply_schema(self._json_body())
        self._send_json(200, {})

    def r_query(self, index: str):
        """Accepts either a raw PQL body or a JSON envelope
        ``{"query": ..., "shards": [...], "remote": bool}`` — the latter
        is the node↔node fan-out form (reference QueryRequest,
        internal/public.proto)."""
        body = self._body()
        remote = False
        profile = False
        shards = None
        pql = body.decode()
        if self.headers.get("Content-Type", "").startswith("application/json"):
            try:
                obj = json.loads(pql or "{}")
            except json.JSONDecodeError:
                obj = None  # raw PQL sent with a JSON content type
            if isinstance(obj, dict):
                pql = obj.get("query", "")
                shards = obj.get("shards")
                remote = bool(obj.get("remote"))
                profile = bool(obj.get("profile"))
        if "shards" in self.query_params:
            shards = [
                int(s)
                for part in self.query_params["shards"]
                for s in part.split(",")
                if s
            ]
        if self.query_params.get("profile", [""])[0].lower() in ("1", "true"):
            profile = True
        self._send_json(
            200,
            self.api.query(
                index, pql, shards=shards, remote=remote, profile=profile
            ),
        )

    def r_create_index(self, index: str):
        body = self._json_body()
        self._send_json(200, self.api.create_index(index, body.get("options", {})))

    def r_get_index(self, index: str):
        self._send_json(200, self.api.index_info(index))

    def r_delete_index(self, index: str):
        self.api.delete_index(index)
        self._send_json(200, {})

    def r_create_field(self, index: str, field: str):
        body = self._json_body()
        self._send_json(200, self.api.create_field(index, field, body.get("options", {})))

    def r_get_field(self, index: str, field: str):
        self._send_json(200, self.api.field_info(index, field))

    def r_delete_field(self, index: str, field: str):
        self.api.delete_field(index, field)
        self._send_json(200, {})

    def r_import_(self, index: str, field: str):
        ctype = self.headers.get("Content-Type", "")
        if ctype.startswith("application/octet-stream"):
            from pilosa_tpu.cluster import wire

            body = self._body()  # transport faults keep their own path
            try:
                req = wire.decode_import(body)
            except Exception as e:
                # malformed client input, not a server fault (the JSON
                # path 400s the same way via _json_body)
                raise ApiError(f"bad binary import payload: {e}")
        else:
            req = self._json_body()
        self.api.import_bits(index, field, req)
        self._send_json(200, {})

    def r_import_roaring(self, index: str, field: str, shard: str):
        clear = self.query_params.get("clear", ["false"])[0] == "true"
        remote = self.query_params.get("remote", ["false"])[0] == "true"
        view = self.query_params.get("view", ["standard"])[0]
        result = self.api.import_roaring(
            index, field, int(shard), self._body(), clear=clear, view=view,
            remote=remote,
        )
        self._send_json(200, result)

    def r_fragments(self):
        self._send_json(200, {"fragments": self.api.fragment_inventory()})

    def r_resize_fetch(self):
        self._send_json(200, self.api.resize_fetch(self._json_body()))

    def r_migrate_begin(self):
        self._send_json(200, self.api.migrate_begin(self._json_body()))

    def r_migrate_chunk(self):
        p = {k: v[0] for k, v in self.query_params.items()}
        data = self.api.migrate_chunk(p["token"], int(p.get("offset", 0)))
        self._send(200, data, content_type="application/octet-stream")

    def r_migrate_delta(self):
        body = self._json_body()
        frame = self.api.migrate_delta(body.get("token", ""))
        self._send(200, frame, content_type="application/octet-stream")

    def r_migrate_end(self):
        body = self._json_body()
        self._send_json(200, self.api.migrate_end(body.get("token", "")))

    def r_migrate_fetch(self):
        self._send_json(200, self.api.migrate_fetch(self._json_body()))

    def r_migrate_finalize(self):
        self._send_json(200, self.api.migrate_finalize(self._json_body()))

    def r_resize_resume(self):
        self._send_json(200, self.api.resize_resume())

    def r_cluster_message(self):
        self._send_json(200, self.api.receive_message(self._json_body()))

    def r_nodes(self):
        self._send_json(200, self.api.hosts())

    def r_attr_blocks(self):
        p = {k: v[0] for k, v in self.query_params.items()}
        self._send_json(
            200, self.api.attr_blocks(p["index"], p.get("field") or None)
        )

    def r_attr_block_data(self):
        self._send_json(200, self.api.attr_block_data(self._json_body()))

    def r_fragment_blocks(self):
        p = {k: v[0] for k, v in self.query_params.items()}
        self._send_json(
            200,
            self.api.fragment_blocks(
                p["index"], p["field"], p.get("view", "standard"), int(p["shard"])
            ),
        )

    def r_fragment_block_data(self):
        body = self._json_body()
        # Binary when the peer accepts it (packed roaring positions);
        # JSON fallback for unencodable row ids or legacy peers.
        if "application/octet-stream" in (self.headers.get("Accept") or ""):
            data = self.api.fragment_block_data_binary(body)
            if data is not None:
                self._send(200, data, content_type="application/octet-stream")
                return
        self._send_json(200, self.api.fragment_block_data(body))

    def r_fragment_data(self):
        p = {k: v[0] for k, v in self.query_params.items()}
        data = self.api.fragment_data(
            p["index"], p["field"], p.get("view", "standard"), int(p["shard"])
        )
        self._send(200, data, content_type="application/octet-stream")

    def r_export(self):
        index = self.query_params.get("index", [None])[0]
        field = self.query_params.get("field", [None])[0]
        if not index or not field:
            raise ApiError("index and field query params required")
        shard = self.query_params.get("shard", [None])[0]
        csv = self.api.export_csv(index, field, int(shard) if shard else None)
        self._send(200, csv.encode(), content_type="text/csv")

    def r_shards_max(self):
        self._send_json(200, self.api.shards_max())

    def r_translate_keys(self):
        body = self._json_body()
        ids = self.api.translate_keys(
            body.get("index", ""), body.get("field", ""), body.get("keys", [])
        )
        self._send_json(200, {"ids": ids})

    def r_translate_ids(self):
        body = self._json_body()
        keys = self.api.translate_ids(
            body.get("index", ""), body.get("field", ""), body.get("ids", [])
        )
        self._send_json(200, {"keys": keys})

    def r_translate_log(self):
        qs = parse_qs(urlparse(self.path).query)
        offset = int(qs.get("offset", ["0"])[0])
        self._send_json(200, self.api.translate_log(offset))

    def r_translate_restore(self):
        body = self._json_body()
        self._send_json(
            200, self.api.translate_restore(body.get("entries", []))
        )

    def r_set_coordinator(self):
        body = self._json_body()
        self._send_json(200, self.api.set_coordinator(body.get("id", "")))

    def r_resize_abort(self):
        self._send_json(200, self.api.resize_abort())

    def r_remove_node(self):
        body = self._json_body()
        self._send_json(200, self.api.resize_remove_node(body.get("id", "")))

    def r_recalculate_caches(self):
        # reference POST /recalculate-caches; counts here are exact and
        # maintained, so there is nothing to rebuild (docs/parity.md)
        self._send_json(200, {})


class Server:
    """HTTP server wrapper: bind, serve in background, close.

    With ``tls_cert``/``tls_key`` the listener speaks HTTPS (reference
    TLS config server/config.go:36-152; node URIs become https://)."""

    def __init__(
        self,
        api: API,
        host: str = "localhost",
        port: int = 10101,
        long_query_time: float = 0.0,
        tls_cert: str | None = None,
        tls_key: str | None = None,
        default_deadline: float = 0.0,
        slow_query_time: float = 0.0,
    ):
        if slow_query_time > 0:
            api.slow_queries.threshold = slow_query_time
        handler = type(
            "BoundHandler",
            (Handler,),
            {
                "api": api,
                "long_query_time": long_query_time,
                "default_deadline": default_deadline,
                "paused": threading.Event(),
            },
        )

        class _Listener(ThreadingHTTPServer):
            # The serving plane holds ~1k concurrent clients parked on
            # the batcher; socketserver's default listen backlog of 5
            # resets connections the accept loop hasn't reached yet.
            request_queue_size = 1024

        self.httpd = _Listener((host, port), handler)
        self.tls = bool(tls_cert)
        if tls_cert:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert, tls_key)
            self.httpd.socket = ctx.wrap_socket(
                self.httpd.socket, server_side=True
            )
        self.api = api
        self._thread: threading.Thread | None = None

    def pause(self) -> None:
        """Stop answering requests (connections drop) until resume() —
        fault injection mirroring pumba pause in the reference's
        internal/clustertests."""
        self.httpd.RequestHandlerClass.paused.set()

    def resume(self) -> None:
        self.httpd.RequestHandlerClass.paused.clear()

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def serve_background(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        self.api.close()
