"""Node runtime: the programmatic API (reference api.go), HTTP transport
(reference http/handler.go), and server composition root (reference
server.go)."""
