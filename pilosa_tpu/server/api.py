"""Programmatic API surface (reference: api.go, 1414 LoC).

Every HTTP route lands here. Methods are **state-gated** exactly like the
reference (api.go:100-124 validAPIMethods + apimethod_string.go): during
STARTING only status-ish methods work; during RESIZING only fragment
transfer and abort. A single node sits in NORMAL.
"""

from __future__ import annotations

import io
import threading
from typing import Any

import numpy as np

from pilosa_tpu import __version__
from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core import timequantum
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.exec.executor import ExecuteError, Executor
from pilosa_tpu.exec.result import result_to_json
from pilosa_tpu.storage import roaring
from pilosa_tpu.storage.disk import HolderStore

# Cluster states (reference cluster.go:46-51).
STATE_STARTING = "STARTING"
STATE_NORMAL = "NORMAL"
STATE_DEGRADED = "DEGRADED"
STATE_RESIZING = "RESIZING"

# Methods valid in non-NORMAL states (reference api.go:100-124).
_STARTING_METHODS = {
    "Status", "Info", "Version", "Schema", "ClusterMessage", "Hosts",
}
_RESIZING_METHODS = {
    "Status", "Info", "Version", "ClusterMessage", "Hosts",
    "FragmentData", "ResizeAbort",
}


class ApiError(Exception):
    def __init__(self, msg: str, code: int = 400):
        super().__init__(msg)
        self.code = code


class NotFoundError(ApiError):
    def __init__(self, msg: str):
        super().__init__(msg, 404)


class ConflictError(ApiError):
    def __init__(self, msg: str):
        super().__init__(msg, 409)


class API:
    """reference api.go:74 NewAPI."""

    def __init__(
        self,
        holder: Holder | None = None,
        store: HolderStore | None = None,
        cluster=None,
    ):
        self.holder = holder or Holder()
        self.store = store
        self.cluster = cluster
        translator = store.translator if store is not None else None
        self.executor = Executor(self.holder, translator=translator)
        self._lock = threading.RLock()
        self.state = STATE_NORMAL

    # -- state gating (reference api.go:100-124) ---------------------------

    def _validate(self, method: str) -> None:
        if self.state == STATE_NORMAL or self.state == STATE_DEGRADED:
            return
        allowed = (
            _STARTING_METHODS if self.state == STATE_STARTING else _RESIZING_METHODS
        )
        if method not in allowed:
            raise ApiError(
                f"api method {method} not allowed in state {self.state}", 503
            )

    # -- queries ------------------------------------------------------------

    def query(self, index: str, pql: str, shards: list[int] | None = None) -> dict:
        """reference api.go:134 Query."""
        self._validate("Query")
        from pilosa_tpu.pql import ParseError

        try:
            results = self.executor.execute(index, pql, shards=shards)
        except (ExecuteError, ParseError, ValueError, TypeError) as e:
            raise ApiError(str(e))
        return {"results": result_to_json(results)}

    # -- schema CRUD (reference api.go:161-495) -----------------------------

    def schema(self) -> dict:
        self._validate("Schema")
        return {"indexes": self.holder.schema()}

    def apply_schema(self, schema: dict) -> None:
        self._validate("ApplySchema")
        self.holder.apply_schema(schema.get("indexes", []))
        self._sync()

    def create_index(self, name: str, options: dict | None = None) -> dict:
        self._validate("CreateIndex")
        options = options or {}
        with self._lock:
            if self.holder.index(name) is not None:
                raise ConflictError("index already exists")
            try:
                idx = self.holder.create_index(
                    name,
                    keys=options.get("keys", False),
                    track_existence=options.get("trackExistence", True),
                )
            except ValueError as e:
                raise ApiError(str(e))
        self._sync()
        return idx.to_dict()

    def delete_index(self, name: str) -> None:
        self._validate("DeleteIndex")
        if not self.holder.delete_index(name):
            raise NotFoundError("index not found")
        if self.store is not None:
            self.store.delete_index_dir(name)

    def index_info(self, name: str) -> dict:
        self._validate("Index")
        idx = self.holder.index(name)
        if idx is None:
            raise NotFoundError("index not found")
        return idx.to_dict()

    def create_field(self, index: str, field: str, options: dict | None = None) -> dict:
        self._validate("CreateField")
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError("index not found")
        if idx.field(field) is not None:
            raise ConflictError("field already exists")
        try:
            f = idx.create_field(field, FieldOptions.from_dict(options or {}))
        except ValueError as e:
            raise ApiError(str(e))
        self._sync()
        return f.to_dict()

    def delete_field(self, index: str, field: str) -> None:
        self._validate("DeleteField")
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError("index not found")
        if not idx.delete_field(field):
            raise NotFoundError("field not found")
        if self.store is not None:
            self.store.delete_field_dir(index, field)

    def field_info(self, index: str, field: str) -> dict:
        self._validate("Field")
        f = self.holder.field(index, field)
        if f is None:
            raise NotFoundError("field not found")
        return f.to_dict()

    # -- imports (reference api.go:919-1112 Import/ImportValue,
    #    :367-427 ImportRoaring) --------------------------------------------

    def import_bits(self, index: str, field: str, req: dict) -> None:
        """JSON bulk import: rowIDs/rowKeys + columnIDs/columnKeys
        (+ timestamps), or columnIDs/columnKeys + values for int fields."""
        self._validate("Import")
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError("index not found")
        f = idx.field(field)
        if f is None:
            raise NotFoundError("field not found")
        translator = self.executor.translator

        cols = req.get("columnIDs")
        if cols is None:
            keys = req.get("columnKeys")
            if keys is None:
                raise ApiError("columnIDs or columnKeys required")
            if not idx.keys:
                raise ApiError("columnKeys given but index does not use keys")
            cols = translator.translate_keys(index, "", keys)
        cols = np.asarray(cols, dtype=np.uint64)

        if "values" in req:
            if not f.is_bsi():
                raise ApiError(f"field {field!r} is not an int field")
            values = np.asarray(req["values"], dtype=np.int64)
            if len(values) != len(cols):
                raise ApiError("columns/values length mismatch")
            lo, hi = int(values.min()) if len(values) else 0, int(values.max()) if len(values) else 0
            if len(values) and (lo < f.options.min or hi > f.options.max):
                raise ApiError("value out of field range")
            f.import_values(cols, values, clear=req.get("clear", False))
        else:
            rows = req.get("rowIDs")
            if rows is None:
                keys = req.get("rowKeys")
                if keys is None:
                    raise ApiError("rowIDs or rowKeys required")
                if not f.keys:
                    raise ApiError("rowKeys given but field does not use keys")
                rows = translator.translate_keys(index, field, keys)
            if len(rows) != len(cols):
                raise ApiError("rows/columns length mismatch")
            timestamps = req.get("timestamps")
            ts = None
            if timestamps is not None:
                ts = [
                    timequantum.parse_time(t) if t else None for t in timestamps
                ]
            f.import_bits(
                np.asarray(rows, dtype=np.uint64),
                cols,
                timestamps=ts,
                clear=req.get("clear", False),
            )
        ef = idx.existence_field()
        if ef is not None and not req.get("clear", False):
            ef.import_bits(np.zeros(len(cols), dtype=np.uint64), cols)

    def import_roaring(self, index: str, field: str, shard: int, data: bytes, clear: bool = False, view: str = VIEW_STANDARD) -> dict:
        """Binary roaring import: the highest-throughput ingest path
        (reference api.go:367-427; call stack SURVEY §3.4)."""
        self._validate("ImportRoaring")
        f = self.holder.field(index, field)
        if f is None:
            raise NotFoundError("field not found")
        try:
            positions = roaring.deserialize(data)
        except roaring.RoaringError as e:
            raise ApiError(f"bad roaring payload: {e}")
        width = f.n_words * 32
        rows = positions // np.uint64(width)
        cols_local = (positions % np.uint64(width)).astype(np.int64)
        v = f.create_view_if_not_exists(view)
        frag = v.create_fragment_if_not_exists(shard)
        changed = frag.import_bits(rows, cols_local, clear=clear)
        idx = self.holder.index(index)
        ef = idx.existence_field() if idx is not None else None
        if ef is not None and not clear and len(cols_local):
            ef.import_bits(
                np.zeros(len(cols_local), dtype=np.uint64),
                cols_local.astype(np.uint64) + np.uint64(shard) * np.uint64(width),
            )
        return {"changed": int(changed)}

    # -- export (reference api.go:499-573 ExportCSV) ------------------------

    def export_csv(self, index: str, field: str, shard: int | None = None) -> str:
        self._validate("ExportCSV")
        f = self.holder.field(index, field)
        if f is None:
            raise NotFoundError("field not found")
        v = f.view(VIEW_STANDARD)
        out = io.StringIO()
        translator = self.executor.translator
        idx = self.holder.index(index)
        if v is not None:
            shards = sorted(v.fragments) if shard is None else [shard]
            for s in shards:
                frag = v.fragment(s)
                if frag is None:
                    continue
                width = frag.shard_width
                for row in frag.row_ids():
                    cols = frag.row_columns(row)
                    for c in cols:
                        col = int(c) + s * width
                        if f.keys:
                            rk = translator.translate_id(index, field, row)
                            row_out = rk
                        else:
                            row_out = row
                        if idx is not None and idx.keys:
                            col_out = translator.translate_id(index, "", col)
                        else:
                            col_out = col
                        out.write(f"{row_out},{col_out}\n")
        return out.getvalue()

    # -- cluster/info (reference api.go:1114-1342) --------------------------

    def status(self) -> dict:
        self._validate("Status")
        nodes = (
            self.cluster.nodes_info()
            if self.cluster is not None
            else [{"id": self._node_id(), "uri": "", "isCoordinator": True, "state": "READY"}]
        )
        return {"state": self.state, "nodes": nodes, "localID": self._node_id()}

    def info(self) -> dict:
        self._validate("Info")
        from pilosa_tpu.shardwidth import SHARD_WIDTH_EXP

        return {"shardWidth": 1 << SHARD_WIDTH_EXP, "shardWidthExp": SHARD_WIDTH_EXP}

    def version(self) -> dict:
        return {"version": __version__}

    def hosts(self) -> list[dict]:
        self._validate("Hosts")
        return self.status()["nodes"]

    def shards_max(self) -> dict:
        """reference api.go MaxShards /internal/shards/max."""
        return {
            "standard": {
                name: max(idx.available_shards(), default=0)
                for name, idx in self.holder.indexes.items()
            }
        }

    def translate_keys(self, index: str, field: str | None, keys: list[str]) -> list[int]:
        self._validate("TranslateKeys")
        return self.executor.translator.translate_keys(index, field or "", keys)

    def _node_id(self) -> str:
        if self.store is not None:
            return self.store.node_id()
        return "local"

    def _sync(self) -> None:
        if self.store is not None:
            self.store.sync()

    def close(self) -> None:
        if self.store is not None:
            self.store.close()
