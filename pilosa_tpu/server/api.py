"""Programmatic API surface (reference: api.go, 1414 LoC).

Every HTTP route lands here. Methods are **state-gated** exactly like the
reference (api.go:100-124 validAPIMethods + apimethod_string.go): during
STARTING only status-ish methods work; during RESIZING only fragment
transfer and abort. A single node sits in NORMAL.
"""

from __future__ import annotations

import io
import logging
import random
import threading
import time
from typing import Any

import numpy as np

from pilosa_tpu import __version__, deadline
from pilosa_tpu.cluster.client import ClientError
from pilosa_tpu.obs import devledger
from pilosa_tpu.obs import events as ev
from pilosa_tpu.obs import qprofile, slo
from pilosa_tpu.server import qos as qos_mod
from pilosa_tpu.testing import faults
from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core import timequantum
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.exec.executor import ExecuteError, Executor
from pilosa_tpu.exec.result import result_to_json
from pilosa_tpu.storage import roaring
from pilosa_tpu.storage.disk import HolderStore

logger = logging.getLogger(__name__)

# Cluster states (reference cluster.go:46-51).
STATE_STARTING = "STARTING"
STATE_NORMAL = "NORMAL"
STATE_DEGRADED = "DEGRADED"
STATE_RESIZING = "RESIZING"

# Methods valid in non-NORMAL states (reference api.go:100-124).
_STARTING_METHODS = {
    "Status", "Info", "Version", "Schema", "ClusterMessage", "Hosts",
}
_RESIZING_METHODS = {
    "Status", "Info", "Version", "ClusterMessage", "Hosts",
    "FragmentData", "ResizeAbort",
}


class ApiError(Exception):
    def __init__(self, msg: str, code: int = 400):
        super().__init__(msg)
        self.code = code


class NotFoundError(ApiError):
    def __init__(self, msg: str):
        super().__init__(msg, 404)


class ConflictError(ApiError):
    def __init__(self, msg: str):
        super().__init__(msg, 409)


class API:
    """reference api.go:74 NewAPI."""

    def __init__(
        self,
        holder: Holder | None = None,
        store: HolderStore | None = None,
        cluster=None,
        client=None,
        broadcaster=None,
        import_workers: int = 2,
        import_queue_depth: int = 16,
        ingest_staging_buffers: int = 4,
        ingest_upload_slots: int = 2,
        max_writes_per_request: int | None = None,
        batch_window: float = 0.002,
        batch_max_size: int = 64,
        rescache_entries: int = 512,
        rescache_promote_hits: int = 3,
        rescache_demote_deltas: int = 64,
        planner_enabled: bool = True,
        qos_enabled: bool = True,
        qos_weights: dict | None = None,
        qos_down_factor: float = 8.0,
        qos_stage_hold: float = 2.0,
        qos_relax_hold: float = 5.0,
        qos_tick_interval: float = 0.25,
        qos_retry_after: float = 1.0,
        qos_aggressor_share: float = 0.5,
    ):
        self.holder = holder or Holder()
        self.store = store
        self.cluster = cluster
        self.client = client
        self.broadcaster = broadcaster
        translator = store.translator if store is not None else None
        self.executor = Executor(
            self.holder,
            translator=translator,
            max_writes_per_request=max_writes_per_request,
            rescache_entries=rescache_entries,
            rescache_promote_hits=rescache_promote_hits,
            rescache_demote_deltas=rescache_demote_deltas,
            planner_enabled=planner_enabled,
        )
        # Cluster-aware execution path (reference executor.go mapReduce);
        # collapses to the local executor on a single node.
        self.dist = None
        if cluster is not None and client is not None:
            from pilosa_tpu.cluster.dist import DistributedExecutor

            self.dist = DistributedExecutor(
                self.holder, cluster, client, translator=translator,
                local_executor=self.executor,
            )
        self._lock = threading.RLock()
        self._state = STATE_NORMAL
        # Slow-query ring (reference long-query-time log line, upgraded
        # to full profiles at /debug/slow-queries); the server sets the
        # threshold from config.
        self.slow_queries = qprofile.SlowQueryLog()
        # Diagnostics collector; NodeServer installs one (reference
        # server.go diagnostics wiring).
        self.diagnostics = None
        # Flight recorder + incident engine; NodeServer installs one
        # (obs/flightrec.py) — None means /debug/incidents serves empty.
        self.flightrec = None
        # Ring-buffer metrics history + trend detectors; NodeServer
        # installs one (obs/history.py) — None 404s /debug/history.
        self.history = None
        # Crash-durable black box; NodeServer installs one when it has
        # a data dir (obs/blackbox.py) — None 404s /debug/postmortem.
        self.blackbox = None
        # Bounded import worker pool: concurrency limit + backpressure
        # (reference api.go:66-96 importWorkerPoolSize default 2,
        # importWorker :313-348; both knobs configurable like the
        # reference's server config).
        from pilosa_tpu.server.importpool import ImportPool

        self.import_pool = ImportPool(
            workers=import_workers, depth=import_queue_depth,
            jobs=self.holder.jobs, stats=self.holder.stats,
        )
        # Staged ingest pipeline over the pool (pilosa_tpu/ingest/):
        # zero-copy decode into staging buffers, sharded coalescing
        # drains, double-buffered host->device uploads.
        from pilosa_tpu.ingest import IngestPipeline

        self.ingest = IngestPipeline(
            self.import_pool,
            stats=self.holder.stats,
            staging_buffers=ingest_staging_buffers,
            upload_slots=ingest_upload_slots,
        )
        # Ingest applies invalidate (or delta-maintain) semantic-cache
        # entries inside the same group-commit — version-precise, never
        # a global flush (exec/rescache.py).
        self.ingest.on_apply = lambda frag: self.executor.rescache.note_write(
            frag.index, frag.field
        )
        # Continuous-batching serving plane (server/batcher.py):
        # concurrent read-only queries coalesce into micro-batched
        # executor dispatches.  ``batch_window<=0`` or ``batch_max_size
        # <=1`` disables it — every query takes the direct path.  On a
        # clustered node the plane wraps the DISTRIBUTED executor, whose
        # execute/execute_batch collapse to the local executor for
        # single-node clusters and dispatch mesh-complete flights as one
        # sharded launch (cluster/dist.py execute_batch).
        from pilosa_tpu.server.batcher import QueryBatcher
        from pilosa_tpu.server.qos import QosGovernor

        self.batcher = None
        self.prefetcher = None
        self.qos = None
        if batch_window > 0 and batch_max_size > 1:
            # Cost-governed multi-tenant admission (server/qos.py):
            # weighted-fair queues debited by measured device-ms, plus
            # the deprioritize/degrade/shed pressure ladder.  The
            # control-loop taps are callables so the flight recorder
            # (installed later by NodeServer) is picked up live.
            self.qos = QosGovernor(
                stats=self.holder.stats,
                weights=qos_weights,
                enabled=qos_enabled,
                down_factor=qos_down_factor,
                stage_hold=qos_stage_hold,
                relax_hold=qos_relax_hold,
                tick_interval=qos_tick_interval,
                retry_after=qos_retry_after,
                aggressor_share=qos_aggressor_share,
                slo_fn=lambda: self.holder.slo,
                ledger_fn=devledger.tenant_totals,
                journal_fn=lambda: self.holder.events,
                incident_fn=lambda trig: (
                    self.flightrec.capture_incident(trig)
                    if self.flightrec is not None
                    else None
                ),
            )
            # Predictive residency prefetch (server/prefetch.py): the
            # batcher's admission queue resolves each flight's cold
            # fragments onto the ingest uploader's low-priority lane, so
            # H2D staging overlaps compute under an oversubscribed HBM
            # budget.  No-op while the budget is uncapped.
            if self.ingest.uploader is not None:
                from pilosa_tpu.server.prefetch import FlightPrefetcher

                self.prefetcher = FlightPrefetcher(
                    self.holder, self.ingest.uploader, self.executor
                )
            self.batcher = QueryBatcher(
                self.dist if self.dist is not None else self.executor,
                stats=self.holder.stats,
                window=batch_window,
                max_batch=batch_max_size,
                prefetcher=self.prefetcher,
                qos=self.qos,
            )
        # Online-migration state (cluster/migration.py): source-side
        # session registry (snapshot cut + delta tap per in-flight
        # fragment transfer) and the target-side held pulls awaiting the
        # post-flip finalize drain.
        from pilosa_tpu.cluster.migration import MigrationRegistry

        self.migrations = MigrationRegistry(self._node_id())
        self._migrate_pulls: dict[tuple, dict] = {}
        self._migrate_lock = threading.Lock()
        # Coordinator-side resume state: in-process mirror of the
        # on-disk resize journal, so storeless clusters can resume an
        # interrupted resize too (cluster/resize.py).
        self._resize_journal: dict | None = None

    @property
    def state(self) -> str:
        if self.cluster is not None and hasattr(self.cluster, "state"):
            return self.cluster.state
        return self._state

    @state.setter
    def state(self, value: str) -> None:
        if self.cluster is not None and hasattr(self.cluster, "set_state"):
            self.cluster.set_state(value)
        else:
            self._state = value

    def _broadcast(self, msg: dict) -> None:
        """Best-effort control-plane fan-out: a peer that misses a schema
        message re-converges via the schema sync pass of anti-entropy
        (the reference re-exchanges full NodeStatus incl. schema on every
        gossip push/pull, gossip.go:321-357). Raising here instead would
        leave the already-committed local mutation un-broadcast forever,
        since a client retry hits ConflictError before re-broadcasting."""
        if self.broadcaster is None:
            return
        try:
            self.broadcaster.send_sync(msg)
        except Exception as e:
            logger.warning("broadcast %s failed: %s", msg.get("type"), e)

    # -- state gating (reference api.go:100-124) ---------------------------

    def _validate(self, method: str) -> None:
        if self.state == STATE_NORMAL or self.state == STATE_DEGRADED:
            return
        allowed = (
            _STARTING_METHODS if self.state == STATE_STARTING else _RESIZING_METHODS
        )
        if method not in allowed:
            raise ApiError(
                f"api method {method} not allowed in state {self.state}", 503
            )

    # -- queries ------------------------------------------------------------

    def query(
        self,
        index: str,
        pql: str,
        shards: list[int] | None = None,
        remote: bool = False,
        profile: bool = False,
    ) -> dict:
        """reference api.go:134 Query. ``remote=True`` marks a mapped
        sub-query from another node's coordinator (reference Remote:true
        QueryRequest): keys arrive pre-translated, results return in wire
        encoding for the caller's reduce step.  ``profile=True`` returns
        the per-query call tree (spans, kernel dispatches, cache hits,
        remote sub-profiles) under ``"profile"`` alongside the results;
        a profile is also collected — without being returned — whenever
        the slow-query log is armed, so threshold breaches capture a
        full tree."""
        self._validate("Query")
        # Fail fast if the budget is already spent (e.g. a forwarded
        # sub-query whose header arrived expired) — DeadlineExceeded is
        # deliberately outside the ApiError catch below so it reaches
        # the transport layer's 504 mapping.
        deadline.check(f"query on {index!r}")
        from pilosa_tpu.pql import ParseError

        if remote:
            # node↔node fan-out sub-query: the user-facing request is
            # already on the coordinator's budget — don't double-count
            # it against a read class on this node.
            slo.note_class(slo.OP_INTERNAL)
        prof = None
        if profile or self.slow_queries.enabled:
            node_id = getattr(self.cluster, "node_id", "") if self.cluster else ""
            prof = qprofile.QueryProfile(index, pql, node_id=node_id)
        t0 = time.perf_counter()
        err = None
        try:
            with qprofile.activate(prof):
                try:
                    if remote and self.dist is not None:
                        from pilosa_tpu.cluster.wire import encode_results

                        results = self.dist.execute_remote(index, pql, shards)
                        resp = {"wireResults": encode_results(results)}
                    else:
                        results = self._execute_query(index, pql, shards)
                        resp = {"results": result_to_json(results)}
                        # Degraded tier is EXPLICIT: a last-known
                        # answer served under QoS pressure stage 2 is
                        # marked in the envelope (server/qos.py sets
                        # the request-scoped note in batcher.submit)
                        if qos_mod.take_degraded():
                            resp["degraded"] = True
                except (ExecuteError, ParseError, ValueError, TypeError) as e:
                    err = str(e)
                    raise ApiError(str(e))
        except BaseException as e:
            if err is None:
                err = repr(e)  # timeouts etc. still land in the slow log
            raise
        finally:
            if prof is not None:
                prof.finish(time.perf_counter() - t0, error=err)
                self.slow_queries.observe(prof)
        if prof is not None and profile:
            resp["profile"] = prof.to_dict()
        return resp

    def _execute_query(self, index: str, pql_text: str, shards):
        """Route one local query: read-only queries ride the
        continuous-batching plane (``batcher.submit`` parks this handler
        thread until its micro-batch lands) when they resolve entirely
        on this node OR onto the local serving mesh — a mesh-complete
        flight dispatches as ONE sharded launch (cluster/dist.py
        execute_batch) instead of N HTTP subrequests.  Writes and
        fan-outs with off-mesh owners keep the direct path — writes for
        strict in-order semantics, off-mesh fan-outs because the
        distributed executor batches per-hop itself (ROADMAP item 4)."""
        from pilosa_tpu import pql

        q = pql.parse(pql_text) if isinstance(pql_text, str) else pql_text
        # SLO op class rides a contextvar to the HTTP layer's recording
        # point (this thread handles the whole request).
        op_class = slo.classify_query(q)
        slo.note_class(op_class)
        # Device cost ledger principal: every launch this query causes —
        # inline, batched (the flight snapshots it at submit), or
        # mesh-dispatched — books under (tenant, index, op_class).
        with devledger.principal_scope(index, op_class):
            batcher = self.batcher
            dist = self.dist
            if batcher is not None and batcher.accepts(q):
                if (
                    dist is None
                    or dist._single
                    or dist.mesh_complete(index, q, shards)
                ):
                    return batcher.submit(index, q, shards=shards)
            if dist is not None:
                return dist.execute(index, q, shards=shards)
            return self.executor.execute(index, q, shards=shards)

    # -- schema CRUD (reference api.go:161-495) -----------------------------

    def schema(self) -> dict:
        self._validate("Schema")
        return {"indexes": self.holder.schema()}

    def apply_schema(self, schema: dict) -> None:
        self._validate("ApplySchema")
        self.holder.apply_schema(schema.get("indexes", []))
        self._sync()

    def create_index(
        self, name: str, options: dict | None = None, broadcast: bool = True
    ) -> dict:
        self._validate("CreateIndex")
        return self._create_index(name, options, broadcast)

    def _create_index(
        self, name: str, options: dict | None = None, broadcast: bool = True
    ) -> dict:
        options = options or {}
        with self._lock:
            if self.holder.index(name) is not None:
                raise ConflictError("index already exists")
            try:
                idx = self.holder.create_index(
                    name,
                    keys=options.get("keys", False),
                    track_existence=options.get("trackExistence", True),
                )
            except ValueError as e:
                raise ApiError(str(e))
        self._sync()
        if broadcast:
            from pilosa_tpu.cluster import broadcast as bc

            self._broadcast(
                {"type": bc.MSG_CREATE_INDEX, "index": name, "options": options}
            )
        return idx.to_dict()

    def delete_index(self, name: str, broadcast: bool = True) -> None:
        self._validate("DeleteIndex")
        self._delete_index(name, broadcast)

    def _delete_index(self, name: str, broadcast: bool = True) -> None:
        if not self.holder.delete_index(name):
            raise NotFoundError("index not found")
        if self.store is not None:
            self.store.delete_index_dir(name)
        if broadcast:
            from pilosa_tpu.cluster import broadcast as bc

            self._broadcast({"type": bc.MSG_DELETE_INDEX, "index": name})

    def index_info(self, name: str) -> dict:
        self._validate("Index")
        idx = self.holder.index(name)
        if idx is None:
            raise NotFoundError("index not found")
        return idx.to_dict()

    def create_field(
        self,
        index: str,
        field: str,
        options: dict | None = None,
        broadcast: bool = True,
    ) -> dict:
        self._validate("CreateField")
        return self._create_field(index, field, options, broadcast)

    def _create_field(
        self,
        index: str,
        field: str,
        options: dict | None = None,
        broadcast: bool = True,
    ) -> dict:
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError("index not found")
        if idx.field(field) is not None:
            raise ConflictError("field already exists")
        try:
            f = idx.create_field(field, FieldOptions.from_dict(options or {}))
        except ValueError as e:
            raise ApiError(str(e))
        self._sync()
        if broadcast:
            from pilosa_tpu.cluster import broadcast as bc

            self._broadcast(
                {
                    "type": bc.MSG_CREATE_FIELD,
                    "index": index,
                    "field": field,
                    "options": options or {},
                }
            )
        return f.to_dict()

    def delete_field(self, index: str, field: str, broadcast: bool = True) -> None:
        self._validate("DeleteField")
        self._delete_field(index, field, broadcast)

    def _delete_field(self, index: str, field: str, broadcast: bool = True) -> None:
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError("index not found")
        if not idx.delete_field(field):
            raise NotFoundError("field not found")
        if self.store is not None:
            self.store.delete_field_dir(index, field)
        if broadcast:
            from pilosa_tpu.cluster import broadcast as bc

            self._broadcast(
                {"type": bc.MSG_DELETE_FIELD, "index": index, "field": field}
            )

    def field_info(self, index: str, field: str) -> dict:
        self._validate("Field")
        f = self.holder.field(index, field)
        if f is None:
            raise NotFoundError("field not found")
        return f.to_dict()

    # -- imports (reference api.go:919-1112 Import/ImportValue,
    #    :367-427 ImportRoaring) --------------------------------------------

    def import_bits(self, index: str, field: str, req: dict) -> None:
        """JSON bulk import: rowIDs/rowKeys + columnIDs/columnKeys
        (+ timestamps), or columnIDs/columnKeys + values for int fields.

        In cluster mode the receiving node acts as import coordinator
        (reference api.go:919-1112): it translates keys once, splits the
        batch by shard, applies the locally-owned slice, and forwards each
        remaining slice to every replica owning its shard (api.go:964-995),
        marked ``remote`` so receivers do not re-forward."""
        self._validate("Import")
        deadline.check(f"import into {index!r}/{field!r}")
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError("index not found")
        f = idx.field(field)
        if f is None:
            raise NotFoundError("field not found")
        translator = self.executor.translator

        cols = req.get("columnIDs")
        if cols is None:
            keys = req.get("columnKeys")
            if keys is None:
                raise ApiError("columnIDs or columnKeys required")
            if not idx.keys:
                raise ApiError("columnKeys given but index does not use keys")
            cols = translator.translate_keys(index, "", keys)
        cols = np.asarray(cols, dtype=np.uint64)

        if not req.get("remote") and self._route_import(index, f, req, cols):
            return
        # The local apply rides the staged ingest pipeline: per-shard
        # segments are submitted to the bounded worker pool (reference
        # api.go:313-348 backpressure semantics) before any is awaited,
        # so distinct fragments drain concurrently while applied
        # fragments upload to the device in the background.  One
        # import-drain record spans the whole request.
        with self.import_pool.drain_scope():
            self._apply_import(idx, f, index, field, req, cols)

    def _apply_import(self, idx, f, index: str, field: str, req: dict, cols) -> None:
        translator = self.executor.translator
        if "values" in req:
            if not f.is_bsi():
                raise ApiError(f"field {field!r} is not an int field")
            values = np.asarray(req["values"], dtype=np.int64)
            if len(values) != len(cols):
                raise ApiError("columns/values length mismatch")
            lo, hi = int(values.min()) if len(values) else 0, int(values.max()) if len(values) else 0
            if len(values) and (lo < f.options.min or hi > f.options.max):
                raise ApiError("value out of field range")
            f.import_values(
                cols, values, clear=req.get("clear", False),
                pipeline=self.ingest,
            )
        else:
            rows = req.get("rowIDs")
            if rows is None:
                keys = req.get("rowKeys")
                if keys is None:
                    raise ApiError("rowIDs or rowKeys required")
                if not f.keys:
                    raise ApiError("rowKeys given but field does not use keys")
                rows = translator.translate_keys(index, field, keys)
            if len(rows) != len(cols):
                raise ApiError("rows/columns length mismatch")
            timestamps = req.get("timestamps")
            ts = None
            if timestamps is not None:
                ts = [
                    timequantum.parse_time(t) if t else None for t in timestamps
                ]
            f.import_bits(
                np.asarray(rows, dtype=np.uint64),
                cols,
                timestamps=ts,
                clear=req.get("clear", False),
                pipeline=self.ingest,
                segments=req.get("_segments"),
            )
        ef = idx.existence_field()
        if ef is not None and not req.get("clear", False):
            ef.import_bits(
                np.zeros(len(cols), dtype=np.uint64), cols,
                pipeline=self.ingest,
            )

    def _route_import(self, index: str, f, req: dict, cols: np.ndarray) -> bool:
        """Cluster import routing (reference api.go:964-995). Returns True
        when the batch was split and dispatched shard-wise to owning
        nodes; False when the caller should apply it wholly locally."""
        if (
            self.cluster is None
            or self.client is None
            or len(self.cluster.nodes) <= 1
        ):
            return False
        translator = self.executor.translator
        values = req.get("values")
        rows = None
        if values is None:
            rows = req.get("rowIDs")
            if rows is None:
                keys = req.get("rowKeys")
                if keys is None:
                    raise ApiError("rowIDs or rowKeys required")
                if not f.keys:
                    raise ApiError("rowKeys given but field does not use keys")
                rows = translator.translate_keys(index, f.name, keys)
            rows = np.asarray(rows, dtype=np.uint64)
            if len(rows) != len(cols):
                raise ApiError("rows/columns length mismatch")
        else:
            values = np.asarray(values, dtype=np.int64)
            if len(values) != len(cols):
                raise ApiError("columns/values length mismatch")
        timestamps = req.get("timestamps")
        width = f.n_words * 32
        shards = cols // np.uint64(width)
        node_masks: dict[str, np.ndarray] = {}
        node_uri: dict[str, str] = {}
        for s in np.unique(shards):
            m = shards == s
            for node in self.cluster.shard_nodes(index, int(s)):
                node_uri[node.id] = node.uri
                node_masks[node.id] = (
                    m if node.id not in node_masks else (node_masks[node.id] | m)
                )
        # Dispatch every node's slice before reporting errors, so one dead
        # replica can't leave later nodes' slices silently undelivered.
        errors: list[str] = []
        for node_id, mask in node_masks.items():
            # numpy slices ride through: the local apply consumes them
            # directly and the client binary-encodes them (JSON fallback
            # listifies; "_width" lets it build roaring positions)
            sub: dict = {
                "columnIDs": cols[mask],
                "remote": True,
                "_width": width,
            }
            if values is not None:
                sub["values"] = values[mask]
            else:
                sub["rowIDs"] = rows[mask]
            if timestamps is not None:
                idxs = np.nonzero(mask)[0]
                sub["timestamps"] = [timestamps[i] for i in idxs]
            if req.get("clear"):
                sub["clear"] = True
            try:
                if node_id == self.cluster.node_id:
                    self.import_bits(index, f.name, sub)
                else:
                    self.client.import_bits(node_uri[node_id], index, f.name, sub)
            except Exception as e:
                errors.append(f"{node_id}: {e}")
        if errors:
            raise ApiError(
                "import partially failed on node(s): " + "; ".join(errors), 500
            )
        return True

    def import_roaring(self, index: str, field: str, shard: int, data: bytes, clear: bool = False, view: str = VIEW_STANDARD, remote: bool = False) -> dict:
        """Binary roaring import: the highest-throughput ingest path
        (reference api.go:367-427; call stack SURVEY §3.4). In cluster
        mode the batch is applied on every replica owning the shard
        (api.go:400-404)."""
        self._validate("ImportRoaring")
        f = self.holder.field(index, field)
        if f is None:
            raise NotFoundError("field not found")
        if (
            not remote
            and self.cluster is not None
            and self.client is not None
            and len(self.cluster.nodes) > 1
        ):
            changed = 0
            errors: list[str] = []
            for node in self.cluster.shard_nodes(index, shard):
                try:
                    if node.id == self.cluster.node_id:
                        changed = self.import_roaring(
                            index, field, shard, data, clear=clear, view=view,
                            remote=True,
                        )["changed"]
                    else:
                        resp = self.client.import_roaring(
                            node.uri, index, field, shard, data, clear=clear,
                            view=view,
                        )
                        # All replicas apply the same batch; any replica's
                        # changed count is THE changed count.
                        if isinstance(resp, dict) and "changed" in resp:
                            changed = resp["changed"]
                except Exception as e:
                    errors.append(f"{node.id}: {e}")
            if errors:
                raise ApiError(
                    "import-roaring failed on replica(s): " + "; ".join(errors),
                    500,
                )
            return {"changed": changed}
        # Staged local apply: zero-copy decode into a staging buffer on
        # this handler thread, a coalesced merge on the import pool
        # (queued same-fragment batches group-commit into one apply; the
        # shared "changed" count is the group total), then a
        # double-buffered device upload overlapping the next batch's
        # merge.  One import-drain record spans the stages.
        with self.import_pool.drain_scope():
            try:
                buf = self.ingest.decode_roaring(data)
            except roaring.RoaringError as e:
                raise ApiError(f"bad roaring payload: {e}")

            def apply_group(payloads):
                # Per-payload merges under ONE pool job: the summed
                # "changed" equals the concat-then-merge count (a bit
                # two payloads both set counts once — the second merge
                # sees it already set), each merge sorts a modest batch
                # instead of one huge concatenation, and the group
                # still pays a single device sync.
                changed = 0
                frag = None
                for b in payloads:
                    result, frag = self._apply_roaring_positions(
                        index, f, shard, b.positions, clear, view
                    )
                    changed += result["changed"]
                return {"changed": changed}, frag

            handle = self.ingest.submit_segment(
                (index, f.name, view, int(shard), bool(clear)),
                buf,
                apply_group,
                release=lambda b: b.release(),
            )
            return handle.wait()

    def _apply_roaring(self, index: str, f, shard: int, data: bytes, clear: bool, view: str) -> dict:
        """Local roaring apply, state-gate-free (also the landing path for
        resize fragment transfers, which run while gated to RESIZING).
        Lock-step variant: decode + apply on the calling thread."""
        try:
            positions = roaring.deserialize(data)
        except roaring.RoaringError as e:
            raise ApiError(f"bad roaring payload: {e}")
        result, _frag = self._apply_roaring_positions(
            index, f, shard, positions, clear, view
        )
        return result

    def _apply_roaring_positions(
        self, index: str, f, shard: int, positions: np.ndarray, clear: bool,
        view: str,
    ) -> tuple[dict, object]:
        """Merge decoded roaring positions into the shard's fragment;
        returns (result, fragment) so the pipeline can hand the applied
        fragment to the device-upload stage."""
        width = f.n_words * 32
        rows = positions // np.uint64(width)
        cols_local = (positions % np.uint64(width)).astype(np.int64)
        v = f.create_view_if_not_exists(view)
        frag = v.create_fragment_if_not_exists(shard)
        changed = frag.import_bits(rows, cols_local, clear=clear)
        if view.startswith("bsig_") and f.is_bsi() and len(rows):
            # Restore bit depth from the transferred planes: schema carries
            # only FieldOptions, and depth auto-grows per node (reference
            # field.go:1050-1067) — without this a resize-transferred int
            # fragment would read as all-zero on the new owner.
            from pilosa_tpu.core.fragment import BSI_OFFSET_BIT

            f.grow_bit_depth(int(rows.max()) - BSI_OFFSET_BIT + 1)
        idx = self.holder.index(index)
        ef = idx.existence_field() if idx is not None else None
        if ef is not None and not clear and len(cols_local):
            ef.import_bits(
                np.zeros(len(cols_local), dtype=np.uint64),
                cols_local.astype(np.uint64) + np.uint64(shard) * np.uint64(width),
            )
        return {"changed": int(changed)}, frag

    # -- export (reference api.go:499-573 ExportCSV) ------------------------

    def export_csv(self, index: str, field: str, shard: int | None = None) -> str:
        self._validate("ExportCSV")
        f = self.holder.field(index, field)
        if f is None:
            raise NotFoundError("field not found")
        v = f.view(VIEW_STANDARD)
        out = io.StringIO()
        translator = self.executor.translator
        idx = self.holder.index(index)
        if v is not None:
            shards = sorted(v.fragments) if shard is None else [shard]
            for s in shards:
                frag = v.fragment(s)
                if frag is None:
                    continue
                width = frag.shard_width
                for row in frag.row_ids():
                    cols = frag.row_columns(row)
                    for c in cols:
                        col = int(c) + s * width
                        if f.keys:
                            rk = translator.translate_id(index, field, row)
                            row_out = rk
                        else:
                            row_out = row
                        if idx is not None and idx.keys:
                            col_out = translator.translate_id(index, "", col)
                        else:
                            col_out = col
                        out.write(f"{row_out},{col_out}\n")
        return out.getvalue()

    # -- cluster/info (reference api.go:1114-1342) --------------------------

    def _nodes_info(self) -> list[dict]:
        if self.cluster is not None:
            return self.cluster.nodes_info()
        return [{"id": self._node_id(), "uri": "", "isCoordinator": True, "state": "READY"}]

    def status(self) -> dict:
        self._validate("Status")
        nodes = self._nodes_info()
        # schema rides along for peer status exchange (the reference's
        # NodeStatus carries schema on gossip push/pull, gossip.go:321-357).
        out = {
            "state": self.state,
            "nodes": nodes,
            "localID": self._node_id(),
            "schema": self.holder.schema(),
            "availableShards": self.available_shards_map(),
        }
        if self.cluster is not None:
            # Resize visibility: followers' watchdogs poll this to tell a
            # coordinator still migrating from one that died mid-resize.
            out["coordinator"] = self.cluster.coordinator_id
            out["epoch"] = self.cluster.epoch
            out["resizePending"] = self.cluster.resize_pending
        return out

    def info(self) -> dict:
        self._validate("Info")
        from pilosa_tpu.shardwidth import SHARD_WIDTH_EXP

        return {"shardWidth": 1 << SHARD_WIDTH_EXP, "shardWidthExp": SHARD_WIDTH_EXP}

    def version(self) -> dict:
        return {"version": __version__}

    def hosts(self) -> list[dict]:
        self._validate("Hosts")
        # Membership only — skip status()'s full schema/shard-map build.
        return self._nodes_info()

    def shards_max(self) -> dict:
        """reference api.go MaxShards /internal/shards/max."""
        return {
            "standard": {
                name: max(idx.available_shards(), default=0)
                for name, idx in self.holder.indexes.items()
            }
        }

    # -- fragment internals (reference api.go:590-660 fragment block
    #    endpoints; used by anti-entropy sync and resize) -------------------

    def _fragment(self, index: str, field: str, view: str, shard: int):
        frag = self.holder.fragment(index, field, view, shard)
        if frag is None:
            raise NotFoundError(
                f"fragment not found: {index}/{field}/{view}/{shard}"
            )
        return frag

    def fragment_blocks(self, index: str, field: str, view: str, shard: int) -> dict:
        self._validate("FragmentBlocks")
        return {"blocks": self._fragment(index, field, view, shard).blocks()}

    def fragment_block_data(self, req: dict) -> dict:
        self._validate("FragmentBlockData")
        frag = self._fragment(
            req["index"], req["field"], req.get("view", VIEW_STANDARD),
            int(req["shard"]),
        )
        rows, cols = frag.block_data(int(req["block"]))
        return {"rows": rows, "cols": cols}

    def fragment_block_data_binary(self, req: dict) -> bytes | None:
        """Packed-binary block payload: the block's set bits as a roaring
        blob of row*width+col positions — a diverged 10M-bit block moves
        as compressed containers instead of JSON int lists (reference
        ships blocks via protobuf, encoding/proto/proto.go). None when a
        row id exceeds the position encoding (caller falls back to
        JSON)."""
        self._validate("FragmentBlockData")
        frag = self._fragment(
            req["index"], req["field"], req.get("view", VIEW_STANDARD),
            int(req["shard"]),
        )
        rows, cols = frag.block_data(int(req["block"]))
        width = frag.shard_width
        max_row = (2**64 - 1 - (width - 1)) // width
        if any(r > max_row for r in rows):
            return None
        positions = np.asarray(rows, dtype=np.uint64) * np.uint64(width) + np.asarray(
            cols, dtype=np.uint64
        )
        return roaring.serialize(np.sort(positions))

    def _attr_store(self, index: str, field: str | None):
        idx = self.holder.index(index)
        if idx is None:
            raise ApiError(f"index not found: {index}", 404)
        if not field:
            return idx.column_attrs
        f = idx.field(field)
        if f is None:
            raise ApiError(f"field not found: {field}", 404)
        return f.row_attrs

    def attr_blocks(self, index: str, field: str | None) -> dict:
        """Attr block checksums for anti-entropy diff (reference
        api.go:590-660 fragment/attr block endpoints; attr.go:81-120)."""
        self._validate("FragmentBlocks")
        store = self._attr_store(index, field)
        return {
            "blocks": [
                {"id": bid, "checksum": chk.hex()}
                for bid, chk in store.blocks()
            ]
        }

    def attr_block_data(self, req: dict) -> dict:
        self._validate("FragmentBlockData")
        store = self._attr_store(req["index"], req.get("field"))
        return {
            "attrs": {
                str(k): v
                for k, v in store.block_data(int(req["block"])).items()
            }
        }

    def fragment_data(self, index: str, field: str, view: str, shard: int) -> bytes:
        """Whole-fragment snapshot as a roaring blob (reference
        api.go FragmentData; fragment.go:2424-2594 tar WriteTo)."""
        self._validate("FragmentData")
        frag = self._fragment(index, field, view, shard)
        return roaring.serialize(frag.all_positions())

    def available_shards_map(self) -> dict:
        """{index: {field: [shards]}} of shards available cluster-wide as
        this node knows them (reference field.go AvailableShards union:
        local + remote)."""
        out: dict = {}
        for iname in self.holder.index_names():
            idx = self.holder.index(iname)
            if idx is None:
                continue
            fields = {}
            for fname in idx.field_names(include_internal=True):
                field = idx.field(fname)
                if field is not None:
                    fields[fname] = sorted(field.available_shards())
            out[iname] = fields
        return out

    def merge_available_shards(self, shard_map: dict) -> None:
        """Merge a peer's (or the resize coordinator's) shard-availability
        map (reference field.go:331-345 AddRemoteAvailableShards)."""
        for iname, fields in (shard_map or {}).items():
            idx = self.holder.index(iname)
            if idx is None:
                continue
            for fname, shards in fields.items():
                field = idx.field(fname)
                if field is not None:
                    field.add_remote_available_shards(shards)

    def fragment_inventory(self) -> list[dict]:
        """Every fragment this node holds, for resize planning (reference
        fragsByHost cluster.go:687)."""
        self._validate("FragmentData")
        out = []
        for iname in self.holder.index_names():
            idx = self.holder.index(iname)
            if idx is None:
                continue
            for fname in idx.field_names(include_internal=True):
                field = idx.field(fname)
                if field is None:
                    continue
                for vname in field.view_names():
                    for shard in sorted(field.view(vname).fragments):
                        out.append(
                            {
                                "index": iname,
                                "field": fname,
                                "view": vname,
                                "shard": shard,
                            }
                        )
        return out

    # -- control-plane observability (events / jobs / fragments) -----------

    def events_since(self, since: int = 0, limit: int | None = None) -> dict:
        """This node's local event journal past cursor ``since``."""
        return self.holder.events.since(since, limit)

    def cluster_events(self, since: int = 0) -> dict:
        """Cluster timeline: fan out to every peer's LOCAL journal and
        merge into one time-ordered view (coordinator view; any node can
        serve it).  Unreachable peers are reported, not fatal —
        a partitioned peer's missing events should read as "missing",
        the same contract as a truncated cursor."""
        local = self.holder.events.since(since)
        per_node = [local["events"]]
        unreachable = []
        if self.cluster is not None and self.client is not None:
            for node in self.cluster.nodes:
                if node.id == self.cluster.node_id or not node.uri:
                    continue
                try:
                    remote = self.client.debug_events(node.uri, since)
                except Exception as e:
                    unreachable.append({"node": node.id, "error": str(e)})
                    continue
                per_node.append(remote.get("events", []))
        merged = ev.merge_timelines(per_node)
        return {
            "events": merged,
            "nodes": len(per_node),
            "unreachable": unreachable,
        }

    def jobs_snapshot(self, kind: str | None = None) -> dict:
        """Background-job records (active + bounded history)."""
        return self.holder.jobs.snapshot(kind)

    def history_query(
        self,
        series=None,
        since: int | None = None,
        step: float | None = None,
        limit: int | None = None,
    ) -> dict | None:
        """This node's local metrics-history window (obs/history.py);
        None when the history plane is disabled."""
        if self.history is None:
            return None
        return self.history.query(
            series=series, since=since, step=step, limit=limit
        )

    def cluster_history(self, series=None, step: float | None = None) -> dict:
        """Cluster-merged metrics history: fan out to every peer's local
        rings and merge into ONE wall-clock-aligned timeline.  Alignment
        comes from downsampling every node onto the same absolute
        ``floor(t/step)*step`` grid (default: the local cadence), so
        sampler phase differences between nodes disappear; attribution
        is preserved by nesting points per node id under each series.
        Unreachable peers are reported, not fatal — same contract as
        cluster_events."""
        step = float(step) if step is not None else (
            self.history.cadence if self.history is not None else 1.0
        )
        local = self.history_query(series=series, step=step)
        merged: dict[str, dict[str, list]] = {}
        nodes: list[str] = []
        unreachable = []

        def fold(node_id: str, snap: dict | None) -> None:
            if not snap:
                return
            nodes.append(node_id)
            for name, pts in snap.get("series", {}).items():
                merged.setdefault(name, {})[node_id] = pts

        local_id = (
            self.cluster.node_id if self.cluster is not None
            else (local or {}).get("node", "")
        )
        fold(local_id, local)
        if self.cluster is not None and self.client is not None:
            for node in self.cluster.nodes:
                if node.id == self.cluster.node_id or not node.uri:
                    continue
                try:
                    remote = self.client.debug_history(
                        node.uri, series=series, step=step
                    )
                except Exception as e:
                    unreachable.append({"node": node.id, "error": str(e)})
                    continue
                fold(remote.get("node") or node.id, remote)
        return {
            "cluster": True,
            "step": step,
            "nodes": nodes,
            "series": merged,
            "unreachable": unreachable,
        }

    def slo_snapshot(self) -> dict:
        """Live per-op-class objective state (/debug/slo)."""
        return self.holder.slo.snapshot()

    def qos_snapshot(self) -> dict:
        """Cost-governed admission state (/debug/qos): per-tenant
        weighted-fair queue rows, ladder stages, shed/degraded counts
        and recent transitions (server/qos.py)."""
        if self.qos is None:
            return {"enabled": False, "tenants": {}, "transitions": []}
        return self.qos.snapshot()

    # -- trace plane (tail-sampled store, /debug/traces) --------------------

    def traces_snapshot(self, limit: int = 100) -> dict:
        """This node's kept-trace summaries + store counters."""
        store = self.holder.traces
        return {
            "traces": store.summaries(limit),
            "store": store.snapshot(),
        }

    def trace_detail(self, trace_id: str) -> dict | None:
        """One kept trace's spans (local view); None when not kept."""
        return self.holder.traces.detail(trace_id)

    def cluster_traces(self, limit: int = 100) -> dict:
        """Kept-trace summaries from every node, merged newest-first
        (same fan-out contract as :meth:`cluster_events`: unreachable
        peers are reported, not fatal)."""
        per_node = [self.holder.traces.summaries(limit)]
        unreachable = []
        if self.cluster is not None and self.client is not None:
            for node in self.cluster.nodes:
                if node.id == self.cluster.node_id or not node.uri:
                    continue
                try:
                    remote = self.client.debug_traces(node.uri, limit=limit)
                except Exception as e:
                    unreachable.append({"node": node.id, "error": str(e)})
                    continue
                per_node.append(remote.get("traces", []))
        merged = [t for traces in per_node for t in traces]
        merged.sort(key=lambda t: t.get("at", 0.0), reverse=True)
        return {
            "traces": merged[:limit],
            "nodes": len(per_node),
            "unreachable": unreachable,
        }

    def cluster_trace(self, trace_id: str) -> dict:
        """Assemble ONE trace cluster-wide: ask every node for the spans
        it holds under this trace id (kept or merely recent — a fast
        remote leg of a slow coordinator trace lives only in the peer's
        recent tier) and merge them into one span list."""
        spans = list(self.holder.traces.spans_for(trace_id))
        detail = self.holder.traces.detail(trace_id)
        nodes = 1
        unreachable = []
        if self.cluster is not None and self.client is not None:
            for node in self.cluster.nodes:
                if node.id == self.cluster.node_id or not node.uri:
                    continue
                try:
                    remote = self.client.debug_trace_spans(node.uri, trace_id)
                except Exception as e:
                    unreachable.append({"node": node.id, "error": str(e)})
                    continue
                spans.extend(remote.get("spans", []))
                nodes += 1
        spans.sort(key=lambda s: (s.get("startUnixMs", 0), s.get("node", "")))
        out = {
            "traceId": trace_id,
            "spans": spans,
            "nodes": nodes,
            "unreachable": unreachable,
        }
        if detail is not None:
            out["summary"] = {k: v for k, v in detail.items() if k != "spans"}
        return out

    def trace_spans(self, trace_id: str) -> dict:
        """Local spans for one trace id (the peer leg of
        :meth:`cluster_trace`)."""
        return {"spans": self.holder.traces.spans_for(trace_id)}

    # -- incident plane (flight recorder, /debug/incidents) -----------------

    def incidents_snapshot(self) -> dict:
        if self.flightrec is None:
            return {"enabled": False, "incidents": []}
        return self.flightrec.incidents_snapshot()

    def incident_detail(self, incident_id: str) -> dict | None:
        if self.flightrec is None:
            return None
        return self.flightrec.incident_detail(incident_id)

    # -- postmortem plane (black box, /debug/postmortem) --------------------

    def postmortem_snapshot(self, postmortem_id: str | None = None) -> dict | None:
        """Sealed crash bundles from this node's black box: the retained
        summaries + the newest bundle in full, or one bundle by id.
        None when the black box is disabled (no data dir) or the id is
        unknown."""
        if self.blackbox is None:
            return None
        if postmortem_id is not None:
            return self.blackbox.postmortem_detail(postmortem_id)
        return self.blackbox.postmortems()

    def cluster_postmortems(self) -> dict:
        """Every node's postmortem summaries, merged newest-first (same
        fan-out contract as :meth:`cluster_events`: unreachable peers
        are reported, not fatal).  Full bundles stay one ``?id=`` GET
        away on the owning node — a cluster merge of multi-MB bundles
        would be the wrong default."""
        local = self.postmortem_snapshot() or {"postmortems": []}
        merged = [
            dict(s, node=s.get("node") or (
                self.cluster.node_id if self.cluster is not None else ""
            ))
            for s in local.get("postmortems", [])
        ]
        nodes = 1
        unreachable = []
        if self.cluster is not None and self.client is not None:
            for node in self.cluster.nodes:
                if node.id == self.cluster.node_id or not node.uri:
                    continue
                try:
                    remote = self.client.debug_postmortem(node.uri)
                except Exception as e:
                    unreachable.append({"node": node.id, "error": str(e)})
                    continue
                nodes += 1
                for s in remote.get("postmortems", []):
                    merged.append(dict(s, node=s.get("node") or node.id))
        merged.sort(key=lambda s: s.get("assembledAt") or 0.0, reverse=True)
        return {
            "cluster": True,
            "postmortems": merged,
            "nodes": nodes,
            "unreachable": unreachable,
        }

    def fragment_details(
        self, index: str | None = None, field: str | None = None
    ) -> dict:
        """Per-fragment storage/residency introspection plus a
        holder-level aggregate and the device budget block
        (/debug/fragments)."""
        from pilosa_tpu.core import membudget, residency

        tracker = residency.default_tracker()
        fragments = []
        now = time.time()
        for iname in self.holder.index_names():
            if index is not None and iname != index:
                continue
            idx = self.holder.index(iname)
            if idx is None:
                continue
            for fname in idx.field_names(include_internal=True):
                if field is not None and fname != field:
                    continue
                fld = idx.field(fname)
                if fld is None:
                    continue
                for vname in fld.view_names():
                    view = fld.view(vname)
                    for shard in sorted(view.fragments):
                        frag = view.fragments[shard]
                        with frag._lock:
                            rows = len(frag._slot_of)
                            host_bytes = frag._host.nbytes
                            device_resident = frag._device is not None
                            device_bytes = (
                                frag._device_nbytes() if device_resident else 0
                            )
                            counts_cached = frag._counts is not None
                            op_n = frag.op_n
                            mut_version = frag.version
                            mut_epoch = frag.epoch
                            res_state = tracker.state_of(frag)
                            res_pinned = frag._res_pinned
                            res_heat = round(tracker.heat_of(frag), 3)
                        store = frag.store
                        last_snap = getattr(store, "last_snapshot_at", None)
                        # version-cached storage stats: repeat /debug/
                        # fragments polls (and the flight planner, which
                        # shares this cache) stop rescanning containers
                        # while the fragment is unchanged
                        prof = frag.container_profile()
                        d = {
                            "index": iname,
                            "field": fname,
                            "view": vname,
                            "shard": shard,
                            "rows": rows,
                            "bits": prof["bits"],
                            "containers": prof["containers"],
                            "hostBytes": host_bytes,
                            "deviceResident": device_resident,
                            "deviceBytes": device_bytes,
                            "countsCached": counts_cached,
                            "opLogLength": op_n,
                            # never resets (op_n rewinds on snapshot
                            # load; version is monotonic for the life of
                            # the fragment object, epoch fences rebuilt
                            # objects) — the cache-correctness pair
                            "version": mut_version,
                            "epoch": mut_epoch,
                            "residency": res_state,
                            "pinned": res_pinned,
                            "heat": res_heat,
                            "lastSnapshotAge": (
                                now - last_snap if last_snap else None
                            ),
                        }
                        fragments.append(d)
        totals = {
            "fragments": len(fragments),
            "bits": sum(f["bits"] for f in fragments),
            "hostBytes": sum(f["hostBytes"] for f in fragments),
            "deviceResident": sum(1 for f in fragments if f["deviceResident"]),
            "deviceBytes": sum(f["deviceBytes"] for f in fragments),
            "opLogLength": sum(f["opLogLength"] for f in fragments),
            "version": sum(f["version"] for f in fragments),
            "pinned": sum(1 for f in fragments if f["pinned"]),
            "staging": sum(
                1 for f in fragments if f["residency"] == residency.STATE_STAGING
            ),
        }
        return {
            "fragments": fragments,
            "totals": totals,
            "device": membudget.default_budget().snapshot(),
            "residency": tracker.snapshot(),
        }

    def resize_fetch(self, req: dict) -> dict:
        """Fetch and install the listed fragments from their source nodes
        (reference followResizeInstruction cluster.go:1272-1381). Runs
        while the cluster is gated to RESIZING."""
        self._validate("FragmentData")
        if self.client is None:
            raise ApiError("no internal client configured", 500)
        if req.get("schema"):
            # Joining node: install schema before fragment transfer
            # (reference cluster.go:1304-1323).
            self.holder.apply_schema(req["schema"])
            self._sync()
        instructions = req.get("instructions", [])
        job = self.holder.jobs.start("resize-fetch")
        job.set_phase("fetch")
        job.set_progress(fragments_total=len(instructions))
        fetched = 0
        try:
            for ins in instructions:
                index, fname = ins["index"], ins["field"]
                f = self.holder.field(index, fname)
                if f is None:
                    raise ApiError(
                        f"resize target missing schema for {index}/{fname}", 500
                    )
                data = self.client.retrieve_fragment(
                    ins["sourceURI"], index, fname, ins["view"], int(ins["shard"])
                )
                self._apply_roaring(
                    index, f, int(ins["shard"]), data, False, ins["view"]
                )
                fetched += 1
                job.advance(fragments_done=1, bytes_moved=len(data))
        except Exception as e:
            job.finish("aborted", error=f"{type(e).__name__}: {e}")
            raise
        job.finish("done")
        return {"fetched": fetched}

    # -- online migration (snapshot stream + op-log catch-up) ---------------
    #
    # Per-fragment migration for the online resize (cluster/resize.py):
    # the target pulls a pinned snapshot cut in resumable chunks
    # (ChunkPrefetcher overlaps fetch with apply, the PR-7 uploader
    # pattern pointed the other way), then replays op-log deltas in
    # bounded catch-up rounds while writes keep landing on the source.
    # Sessions stay open on the source until the post-flip finalize
    # drain.  ``faults.stage_fault`` hooks mark every phase boundary so
    # chaos tests can kill any participant at any point.

    _CATCHUP_ROUNDS = 5
    _SOURCE_ATTEMPTS = 3

    def _migration(self, token: str):
        try:
            return self.migrations.get(token)
        except KeyError as e:
            raise NotFoundError(str(e))

    def migrate_begin(self, req: dict) -> dict:
        """Source side: open a migration session — pin a snapshot cut
        and install the op-log delta tap (cluster/migration.py)."""
        self._validate("FragmentData")
        faults.stage_fault("source:begin")
        index, field = req["index"], req["field"]
        view = req.get("view", VIEW_STANDARD)
        shard = int(req["shard"])
        frag = self._fragment(index, field, view, shard)
        session = self.migrations.begin(frag, (index, field, view, shard))
        session.chunk_bytes = int(req.get("chunkBytes") or 0) or None
        return {
            "token": session.token,
            "size": session.size,
            "opN": int(getattr(frag, "op_n", 0)),
        }

    def migrate_chunk(self, token: str, offset: int) -> bytes:
        """Source side: one snapshot chunk.  Offset-addressed reads are
        idempotent, so a retried/restarted target resumes mid-stream."""
        self._validate("FragmentData")
        faults.stage_fault("source:chunk")
        session = self._migration(token)
        from pilosa_tpu.cluster import migration

        return session.chunk(
            int(offset), session.chunk_bytes or migration.CHUNK_BYTES
        )

    def migrate_delta(self, token: str) -> bytes:
        """Source side: drain one op-log catch-up round as a binary
        migrate frame (header carries ops-in-blob + ops still pending)."""
        self._validate("FragmentData")
        faults.stage_fault("source:delta")
        session = self._migration(token)
        blob, count, pending = session.delta()
        from pilosa_tpu.cluster import wire

        return wire.encode_migrate_frame(
            {"ops": count, "pending": pending}, blob
        )

    def migrate_end(self, token: str) -> dict:
        """Source side: close a session (uninstalls the delta tap)."""
        self._validate("FragmentData")
        self.migrations.end(token)
        return {}

    def migrate_fetch(self, req: dict) -> dict:
        """Target side: pull every listed fragment (snapshot stream +
        catch-up rounds) and HOLD the source sessions open; the
        coordinator flips ownership, then ``migrate_finalize`` drains
        the tail.  A crash here aborts only this target's instructions —
        its held source sessions expire via the registry TTL."""
        self._validate("FragmentData")
        if self.client is None:
            raise ApiError("no internal client configured", 500)
        if req.get("schema"):
            # Joining node: install schema before any fragment lands
            # (reference cluster.go:1304-1323).
            self.holder.apply_schema(req["schema"])
            self._sync()
        instructions = req.get("instructions", [])
        job = self.holder.jobs.start(
            "migrate-fetch", fragments=len(instructions)
        )
        job.set_phase("snapshot")
        job.set_progress(fragments_total=len(instructions))
        pulls = []
        try:
            for ins in instructions:
                pulls.append(self._migrate_pull(ins, job))
                job.advance(fragments_done=1)
        except Exception as e:
            for p in pulls:
                try:
                    self.client.migrate_end(p["uri"], p["token"])
                except Exception:  # graftlint: disable=exception-hygiene -- best-effort cleanup of held source sessions; the TTL sweep covers the rest
                    pass
            job.finish("aborted", error=f"{type(e).__name__}: {e}")
            raise
        with self._migrate_lock:
            for p in pulls:
                self._migrate_pulls[p["key"]] = p
        job.finish("done")
        return {"fetched": len(pulls)}

    def _migrate_pull(self, ins: dict, job) -> dict:
        """Pull one fragment, trying each listed source holder in turn
        (a dead source retries with seeded backoff, then the next
        replica takes over)."""
        import zlib as _zlib

        from pilosa_tpu.cluster.migration import CHUNK_BYTES

        index, fname = ins["index"], ins["field"]
        view = ins.get("view", VIEW_STANDARD)
        shard = int(ins["shard"])
        f = self.holder.field(index, fname)
        if f is None:
            raise ApiError(
                f"migrate target missing schema for {index}/{fname}", 500
            )
        sources = list(ins.get("sourceURIs") or [])
        if ins.get("sourceURI") and ins["sourceURI"] not in sources:
            sources.append(ins["sourceURI"])
        if not sources:
            raise ApiError(f"no source for {index}/{fname}/{shard}", 500)
        chunk_bytes = int(ins.get("chunkBytes") or CHUNK_BYTES)
        # Seeded by the fragment key: a chaos run's retry cadence
        # replays identically (testing/faults.py contract).
        rng = random.Random(
            _zlib.crc32(f"{index}/{fname}/{view}/{shard}".encode())
        )
        last_err: Exception | None = None
        for uri in sources:
            for attempt in range(self._SOURCE_ATTEMPTS):
                try:
                    return self._migrate_pull_from(
                        uri, index, f, view, shard, chunk_bytes, job
                    )
                except (ClientError, OSError) as e:
                    last_err = e
                    if attempt < self._SOURCE_ATTEMPTS - 1:
                        time.sleep(
                            0.05 * (2 ** attempt) * (0.5 + rng.random())
                        )
            logger.warning(
                "migrate pull of %s/%s/%s/%s from %s failed: %s",
                index, fname, view, shard, uri, last_err,
            )
        raise ApiError(
            f"migrate pull failed from every source for "
            f"{index}/{fname}/{view}/{shard}: {last_err}", 500
        )

    def _migrate_pull_from(
        self, uri: str, index: str, f, view: str, shard: int,
        chunk_bytes: int, job,
    ) -> dict:
        from pilosa_tpu.ingest.pipeline import ChunkPrefetcher

        begin = self.client.migrate_begin(
            uri, index, f.name, view, shard, chunk_bytes=chunk_bytes
        )
        token, size = begin["token"], int(begin["size"])
        try:
            buf = bytearray()
            pf = ChunkPrefetcher(
                lambda off: self.client.migrate_chunk(uri, token, off),
                size=size, chunk_bytes=chunk_bytes,
            )
            try:
                for _off, blob in pf:
                    buf += blob
                    job.advance(bytes_moved=len(blob))
            finally:
                pf.close()
            faults.stage_fault("target:apply")
            if buf:
                self._apply_roaring(index, f, shard, bytes(buf), False, view)
            # Bounded catch-up: writes kept landing on the source during
            # the snapshot stream; replay the accrued op-log delta until
            # lag reaches zero (or rounds exhaust — the post-flip
            # finalize drain is the backstop either way).
            job.set_phase("catch-up")
            lag = 0
            for _round in range(self._CATCHUP_ROUNDS):
                faults.stage_fault("target:catchup")
                header, blob = self.client.migrate_delta(uri, token)
                if blob:
                    self._apply_delta_ops(index, f, shard, view, blob)
                lag = int(header.get("pending", 0))
                job.annotate(
                    catchup_lag=lag, catchup_ops=int(header.get("ops", 0))
                )
                if lag == 0:
                    break
            return {
                "key": (index, f.name, view, shard),
                "uri": uri,
                "token": token,
                "lag": lag,
            }
        except Exception:
            try:
                self.client.migrate_end(uri, token)
            except Exception:  # graftlint: disable=exception-hygiene -- cleanup of a failed pull; the session TTL covers an unreachable source
                pass
            raise

    def _apply_delta_ops(
        self, index: str, f, shard: int, view: str, blob: bytes
    ) -> int:
        """Replay raw op-log records IN ORDER onto the local fragment —
        the catch-up half of migration.  In-order replay makes overlap
        with the snapshot cut harmless: the same ops apply in the same
        order the source applied them, and set/clear are idempotent."""
        applied = 0
        for op_type, payload, _opn in roaring.decode_ops(blob, 0):
            if op_type in (roaring.OP_ADD, roaring.OP_REMOVE):
                positions = np.array([payload], dtype=np.uint64)
            elif op_type in (roaring.OP_ADD_BATCH, roaring.OP_REMOVE_BATCH):
                positions = np.asarray(payload, dtype=np.uint64)
            else:
                positions = roaring.deserialize(payload)
            if not len(positions):
                continue
            clear = op_type in (
                roaring.OP_REMOVE, roaring.OP_REMOVE_BATCH,
                roaring.OP_REMOVE_ROARING,
            )
            self._apply_roaring_positions(
                index, f, shard, positions, clear, view
            )
            applied += 1
        return applied

    def migrate_finalize(self, req: dict) -> dict:
        """Target side, post-flip: drain the final op-log delta from
        each held source session and close it.  An unreachable source
        is non-fatal — anti-entropy heals whatever tail it buffered."""
        self._validate("FragmentData")
        instructions = req.get("instructions")
        with self._migrate_lock:
            if instructions is None:
                pulls = list(self._migrate_pulls.values())
                self._migrate_pulls.clear()
            else:
                pulls = []
                for ins in instructions:
                    key = (
                        ins["index"], ins["field"],
                        ins.get("view", VIEW_STANDARD), int(ins["shard"]),
                    )
                    p = self._migrate_pulls.pop(key, None)
                    if p is not None:
                        pulls.append(p)
        drained = 0
        for p in pulls:
            faults.stage_fault("target:finalize")
            index, fname, view, shard = p["key"]
            f = self.holder.field(index, fname)
            try:
                _header, blob = self.client.migrate_delta(
                    p["uri"], p["token"]
                )
                if blob and f is not None:
                    drained += self._apply_delta_ops(
                        index, f, int(shard), view, blob
                    )
                self.client.migrate_end(p["uri"], p["token"])
            except (ClientError, OSError) as e:
                logger.warning(
                    "finalize drain of %s from %s failed (anti-entropy"
                    " heals the tail): %s", p["key"], p["uri"], e,
                )
        return {"finalized": len(pulls), "ops": drained}

    def _clean_unowned_fragments(self) -> int:
        """Drop fragments this node no longer owns after a membership
        change (reference holderCleaner holder.go:898-926)."""
        if self.cluster is None or not hasattr(self.cluster, "owns_shard"):
            return 0
        dropped = 0
        for iname in self.holder.index_names():
            idx = self.holder.index(iname)
            if idx is None:
                continue
            for fname in idx.field_names(include_internal=True):
                field = idx.field(fname)
                if field is None:
                    continue
                for vname in field.view_names():
                    view = field.view(vname)
                    for shard in sorted(view.fragments):
                        if not self.cluster.owns_shard(
                            self.cluster.node_id, iname, shard
                        ):
                            view.drop_fragment(shard)
                            if self.store is not None:
                                self.store.delete_fragment(
                                    iname, fname, vname, shard
                                )
                            dropped += 1
        return dropped

    def receive_message(self, msg: dict) -> dict:
        """Handle a typed control-plane message from a peer (reference
        Server.receiveMessage switch, server.go:549-643)."""
        self._validate("ClusterMessage")
        from pilosa_tpu.cluster import broadcast as bc

        # Handlers call the _-prefixed internals: a cluster message must
        # apply even when this node's own state gates the public method
        # (e.g. a peer in STARTING receiving schema from the coordinator).
        t = msg.get("type")
        if t == bc.MSG_CREATE_INDEX:
            try:
                self._create_index(msg["index"], msg.get("options"), broadcast=False)
            except ConflictError:
                pass
        elif t == bc.MSG_DELETE_INDEX:
            try:
                self._delete_index(msg["index"], broadcast=False)
            except NotFoundError:
                pass
        elif t == bc.MSG_CREATE_FIELD:
            idx = self.holder.index(msg["index"])
            if idx is not None:
                try:
                    self._create_field(
                        msg["index"], msg["field"], msg.get("options"),
                        broadcast=False,
                    )
                except ConflictError:
                    pass
        elif t == bc.MSG_DELETE_FIELD:
            try:
                self._delete_field(msg["index"], msg["field"], broadcast=False)
            except NotFoundError:
                pass
        elif t == bc.MSG_CREATE_VIEW:
            f = self.holder.field(msg["index"], msg["field"])
            if f is not None:
                f.create_view_if_not_exists(msg["view"])
        elif t == bc.MSG_CREATE_SHARD:
            f = self.holder.field(msg["index"], msg["field"])
            if f is not None:
                f.add_remote_available_shards([int(msg["shard"])])
        elif t == bc.MSG_CLUSTER_STATUS:
            if self.cluster is not None and hasattr(self.cluster, "set_state"):
                nodes = msg.get("nodes")
                if nodes:
                    # Membership commit from the resize coordinator
                    # (reference mergeClusterStatus cluster.go:1918-1978).
                    from pilosa_tpu.cluster.topology import Node as CNode

                    if msg.get("coordinator"):
                        self.cluster.coordinator_id = msg["coordinator"]
                    self.cluster.disabled = False
                    old_ids = {n.id for n in self.cluster.nodes}
                    new_ids = {n["id"] for n in nodes}
                    for nid in sorted(new_ids - old_ids):
                        self.holder.events.record(ev.EVENT_NODE_JOIN, peer=nid)
                    for nid in sorted(old_ids - new_ids):
                        self.holder.events.record(ev.EVENT_NODE_LEAVE, peer=nid)
                    self.cluster.set_static(
                        [CNode(id=n["id"], uri=n.get("uri", "")) for n in nodes]
                    )
                self.cluster.set_state(msg["state"])
                if msg.get("availableShards"):
                    self.merge_available_shards(msg["availableShards"])
                still_member = not nodes or any(
                    n["id"] == self.cluster.node_id for n in nodes
                )
                if nodes and msg["state"] == STATE_NORMAL and still_member:
                    # A removed node keeps its data (the reference expects
                    # it to shut down; its fragments were re-sourced).
                    self._clean_unowned_fragments()
        elif t == bc.MSG_RESIZE_PREPARE:
            # Per-fragment migration begins: remember the PENDING
            # membership + epoch so flips can route flipped shards onto
            # the new ring while everything else stays put.  The cluster
            # state stays NORMAL — reads and writes keep flowing.
            if self.cluster is not None and hasattr(self.cluster, "begin_resize"):
                from pilosa_tpu.cluster.topology import Node as CNode

                pending = [
                    CNode(id=n["id"], uri=n.get("uri", ""))
                    for n in msg.get("nodes", [])
                ]
                epoch = self.cluster.begin_resize(pending, msg.get("epoch"))
                self.holder.events.record(
                    ev.EVENT_RESIZE_PHASE, phase="prepare", epoch=epoch,
                )
        elif t == bc.MSG_EPOCH_FLIP:
            # One shard's ownership flips to the pending ring.
            if self.cluster is not None and hasattr(self.cluster, "flip_shard"):
                if self.cluster.flip_shard(
                    msg["index"], int(msg["shard"]), msg.get("epoch")
                ):
                    self.holder.events.record(
                        ev.EVENT_EPOCH_FLIP,
                        index=msg["index"], shard=int(msg["shard"]),
                        epoch=msg.get("epoch"),
                    )
        elif t == bc.MSG_RESIZE_CANCEL:
            if self.cluster is not None and hasattr(self.cluster, "abort_resize"):
                self.cluster.abort_resize()
                self.holder.events.record(
                    ev.EVENT_RESIZE_ABORT, reason=msg.get("reason", ""),
                )
        elif t == bc.MSG_NODE_STATE:
            if self.cluster is not None and hasattr(self.cluster, "mark_node_state"):
                self.cluster.mark_node_state(msg["node"], msg["state"])
        elif t == bc.MSG_SET_COORDINATOR:
            # coordinator (= translation primary) moved (reference
            # SetCoordinatorMessage handling, server.go:549-643)
            if self.cluster is not None and msg.get("coordinator"):
                self.cluster.coordinator_id = msg["coordinator"]
                for n in self.cluster.nodes:
                    n.is_coordinator = n.id == msg["coordinator"]
        elif t == bc.MSG_RECALCULATE_CACHES:
            pass  # device row counts are exact; no cache to rebuild
        return {}

    def translate_keys(self, index: str, field: str | None, keys: list[str]) -> list[int]:
        self._validate("TranslateKeys")
        return self.executor.translator.translate_keys(index, field or "", keys)

    def translate_ids(self, index: str, field: str | None, ids: list[int]) -> list[str]:
        self._validate("TranslateKeys")
        return self.executor.translator.translate_ids(index, field or "", ids)

    def translate_log(self, offset: int) -> dict:
        """Entry-log feed for replica streaming (reference
        translate.go:91-97): entries since ``offset`` from the LOCAL
        store plus its total length (replicas detect a restarted/
        shorter primary log by the length)."""
        self._validate("TranslateKeys")
        translator = self.executor.translator
        local = getattr(translator, "local", translator)
        entries, new_offset = local.log_entries(int(offset))
        return {
            "entries": [list(e) for e in entries],
            "offset": new_offset,
            "len": local.log_len(),
        }

    def translate_restore(self, entries: list) -> dict:
        """Install exact (index, field, key, id) mappings — the restore
        half of backup's translation dump (set_mapping bypasses
        read-only, the same path replica streaming uses).  In cluster
        mode the restore is FORWARDED to the translation primary: only
        its store allocates future ids, so installing on a replica
        alone would let the primary re-allocate colliding ids; replicas
        then converge via log streaming."""
        self._validate("TranslateKeys")
        translator = self.executor.translator
        if (
            self.cluster is not None
            and self.client is not None
            and hasattr(translator, "_is_primary")
            and not translator._is_primary()
        ):
            primary = self.cluster.translate_primary()
            return self.client.translate_restore(primary.uri, entries)
        local = getattr(translator, "local", translator)
        for index, field, key, id_ in entries:
            local.set_mapping(index, field, [key], [int(id_)])
        return {"restored": len(entries)}

    def resize_abort(self) -> dict:
        """Abort/clear a resize: re-commit the CURRENT membership with
        state NORMAL on every reachable node (reference api.go:1249
        ResizeAbort).  Our resize runs synchronously and self-aborts on
        failure, so this is the operator's recovery hammer for a
        cluster left in RESIZING by a mid-resize coordinator crash.
        Valid only on the coordinator."""
        self._validate("ResizeAbort")
        if self.cluster is None:
            raise ApiError("cluster not configured", 400)
        if not self.cluster.is_coordinator:
            raise ApiError("resize-abort must run on the coordinator", 400)
        from pilosa_tpu.cluster.resize import ResizeCoordinator

        rc = ResizeCoordinator(self.cluster, self.client, self)
        nodes = list(self.cluster.nodes)
        rc._commit_membership(nodes, nodes)
        # The operator chose to abandon the interrupted plan: drop the
        # journal so a later resume() can't replay a dead resize.
        rc._delete_journal()
        return {"aborted": True}

    def resize_remove_node(self, node_id: str) -> dict:
        """Remove a node through the resize protocol (reference
        api.go:1214 RemoveNode + POST /cluster/resize/remove-node).
        Valid only on the coordinator."""
        self._validate("RemoveNode")
        if self.cluster is None:
            raise ApiError("cluster not configured", 400)
        if not self.cluster.is_coordinator:
            raise ApiError("remove-node must run on the coordinator", 400)
        if self.cluster.node(node_id) is None:
            raise ApiError(f"unknown node: {node_id}", 400)
        from pilosa_tpu.cluster.resize import ResizeCoordinator, ResizeError

        try:
            ResizeCoordinator(self.cluster, self.client, self).remove_node(
                node_id
            )
        except ResizeError as e:
            raise ApiError(str(e), 400)
        return {"removed": node_id}

    def resize_resume(self) -> dict:
        """Resume an interrupted resize from the persisted journal (a
        coordinator crash mid-migration leaves a resumable plan behind;
        re-dispatch is idempotent).  Valid only on the coordinator."""
        if self.cluster is None:
            raise ApiError("cluster not configured", 400)
        if not self.cluster.is_coordinator:
            raise ApiError("resize-resume must run on the coordinator", 400)
        from pilosa_tpu.cluster.resize import ResizeCoordinator, ResizeError

        try:
            return ResizeCoordinator(self.cluster, self.client, self).resume()
        except ResizeError as e:
            raise ApiError(str(e), 400)

    def set_coordinator(self, node_id: str) -> dict:
        """Move the coordinator (and with it the translation-primary
        role) to ``node_id``, broadcasting so every live node converges
        (reference api.go:1192-1256 SetCoordinator + the
        SetCoordinatorMessage broadcast).  Used for takeover after a
        dead coordinator: any surviving node accepts this call."""
        if self.cluster is None:
            raise ApiError("cluster not configured", 400)
        if self.cluster.node(node_id) is None:
            raise ApiError(f"unknown node: {node_id}", 400)
        import pilosa_tpu.cluster.broadcast as bc

        self.cluster.coordinator_id = node_id
        for n in self.cluster.nodes:
            n.is_coordinator = n.id == node_id
        if self.broadcaster is not None:
            try:
                self.broadcaster.send_sync(
                    {"type": bc.MSG_SET_COORDINATOR, "coordinator": node_id}
                )
            except Exception:
                # best-effort: takeover typically runs BECAUSE a node is
                # dead; survivors converged, the dead node re-learns the
                # coordinator from ClusterStatus on rejoin
                logger.warning(
                    "set-coordinator broadcast incomplete", exc_info=True
                )
        return {"coordinator": node_id}

    def _node_id(self) -> str:
        if self.store is not None:
            return self.store.node_id()
        return "local"

    def _sync(self) -> None:
        if self.store is not None:
            self.store.sync()

    def close(self) -> None:
        self.migrations.close()  # detach any live delta taps
        if self.flightrec is not None:
            self.flightrec.stop()
        if self.batcher is not None:
            self.batcher.close()  # drains the admission queue first
        self.ingest.close()  # flush pending device uploads
        self.import_pool.close()
        if self.store is not None:
            self.store.close()
