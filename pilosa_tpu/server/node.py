"""Node composition root (reference: server.go Server/NewServer —
wires holder, cluster, executor, transport into one cluster member).

A ``NodeServer`` is one host process of a cluster: it owns a Holder
(backed by a data dir when given), a Cluster view of the membership, an
InternalClient for node↔node traffic, an HTTPBroadcaster for the control
plane, and the HTTP listener. A standalone node (no ``join_static``)
behaves exactly like the single-node server (the reference's
cluster-disabled mode, server.go OptServerClusterDisabled).
"""

from __future__ import annotations

import logging
import uuid
import zlib

from pilosa_tpu.cluster import broadcast as bc
from pilosa_tpu.cluster.broadcast import HTTPBroadcaster
from pilosa_tpu.cluster.client import InternalClient
from pilosa_tpu.cluster.cluster import Cluster
from pilosa_tpu.cluster.topology import Node
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.obs import events as ev
from pilosa_tpu.server.api import API
from pilosa_tpu.server.http import Server

logger = logging.getLogger(__name__)
from pilosa_tpu.shardwidth import SHARD_WORDS
from pilosa_tpu.storage.disk import HolderStore


class ResizeWatchdog:
    """Follower-side backstop for a coordinator that dies mid-resize.

    A node that received MSG_RESIZE_PREPARE but never hears the commit
    or cancel would hold its pending membership forever (the legacy
    equivalent: a node gated in RESIZING with nobody left to lift the
    gate).  This loop watches for resize state that outlives
    ``deadline`` and then re-requests the cluster status straight from
    the coordinator:

    * coordinator reachable and still resizing -> not stuck; re-arm.
    * coordinator reachable, no resize in flight -> this node missed
      the commit/cancel broadcast; apply the authoritative status
      (membership + state) as if the broadcast had arrived.
    * coordinator unreachable -> keep the pending state (the data is
      still placed on the current ring) and retry next deadline; the
      operator path is set_coordinator + resize resume/abort.

    Every action lands on the event journal as ``resize-watchdog``.
    """

    def __init__(self, node: "NodeServer", deadline: float = 15.0,
                 interval: float = 2.0):
        self.node = node
        self.deadline = float(deadline)
        self.interval = min(float(interval), max(0.05, self.deadline / 3))
        self._since: float | None = None
        self._stop = None
        self._thread = None

    def start(self) -> None:
        import threading

        if self._thread is not None:
            return
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="resize-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._tick()
            except Exception:  # graftlint: disable=exception-hygiene -- watchdog must outlive any single bad tick
                logger.exception("resize watchdog tick failed")

    def _tick(self) -> None:
        import time

        from pilosa_tpu.cluster.cluster import STATE_RESIZING

        cluster = self.node.cluster
        stuck = cluster.resize_pending or cluster.state == STATE_RESIZING
        if not stuck or cluster.is_coordinator:
            # The coordinator's own pending state is the resize journal's
            # concern (resume/abort), not the watchdog's.
            self._since = None
            return
        now = time.monotonic()
        if self._since is None:
            self._since = now
            return
        if now - self._since < self.deadline:
            return
        self._since = now  # one probe per deadline window
        coord = cluster.node(cluster.coordinator_id)
        journal = self.node.holder.events
        if coord is None or not coord.uri:
            journal.record(
                ev.EVENT_RESIZE_WATCHDOG, action="no-coordinator",
                coordinator=cluster.coordinator_id,
            )
            return
        try:
            status = self.node.client.status(coord.uri)
        except Exception as e:
            journal.record(
                ev.EVENT_RESIZE_WATCHDOG, action="coordinator-unreachable",
                coordinator=coord.id, error=f"{type(e).__name__}: {e}",
            )
            return
        if status.get("resizePending"):
            # Coordinator alive and mid-migration: a long resize is not a
            # stuck resize.
            journal.record(
                ev.EVENT_RESIZE_WATCHDOG, action="still-resizing",
                coordinator=coord.id,
            )
            return
        # The coordinator has no resize in flight — this node missed the
        # commit or cancel.  Apply its authoritative status as if the
        # broadcast had arrived.
        self.node.api.receive_message(
            {
                "type": bc.MSG_CLUSTER_STATUS,
                "state": status.get("state", cluster.state),
                "coordinator": status.get("coordinator", coord.id),
                "nodes": status.get("nodes") or [],
                "availableShards": status.get("availableShards"),
            }
        )
        journal.record(
            ev.EVENT_RESIZE_WATCHDOG, action="recovered",
            coordinator=coord.id, state=status.get("state"),
        )


class NodeServer:
    def __init__(
        self,
        data_dir: str | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        replica_n: int = 1,
        n_words: int = SHARD_WORDS,
        long_query_time: float = 0.0,
        stats_client=None,
        metric_poll_interval: float = 10.0,
        tls_cert: str | None = None,
        tls_key: str | None = None,
        tls_skip_verify: bool = False,
        tls_ca_cert: str | None = None,
        import_workers: int = 2,
        import_queue_depth: int = 16,
        ingest_staging_buffers: int = 4,
        ingest_upload_slots: int = 2,
        max_writes_per_request: int | None = None,
        default_deadline: float = 0.0,
        client_timeout: float = 30.0,
        client_retry_budget: int = 2,
        breaker_threshold: int = 5,
        breaker_cooldown: float = 2.0,
        slow_query_time: float = 0.0,
        batch_window: float = 0.002,
        batch_max_size: int = 64,
        rescache_entries: int = 512,
        rescache_promote_hits: int = 3,
        rescache_demote_deltas: int = 64,
        planner_enabled: bool = True,
        slo_objectives: dict | None = None,
        slo_burn_rules: list[dict] | None = None,
        slo_slot_seconds: float | None = None,
        slo_latency_window: float | None = None,
        trace_store_capacity: int = 256,
        trace_baseline_n: int = 128,
        flight_recorder: bool = True,
        flightrec_segment_seconds: float = 1.0,
        flightrec_sample_interval: float = 0.025,
        flightrec_segments: int = 60,
        flightrec_spike_504: int = 5,
        history_enabled: bool = True,
        history_cadence: float = 1.0,
        history_tiers: str = "300@1,240@15",
        history_detectors: str = "latency,throughput,errors",
        history_warmup: int = 10,
        history_trips: int = 3,
        history_latency_factor: float = 2.0,
        history_latency_min_ms: float = 20.0,
        resize_watchdog_deadline: float = 15.0,
        mesh_dispatch: bool = True,
        device_budget: int | None = None,
        devledger_storm_threshold: int = 8,
        devledger_storm_window: float = 60.0,
        devledger_warmup: float = 120.0,
        qos_enabled: bool = True,
        qos_weights: dict | None = None,
        qos_down_factor: float = 8.0,
        qos_stage_hold: float = 2.0,
        qos_relax_hold: float = 5.0,
        qos_tick_interval: float = 0.25,
        qos_retry_after: float = 1.0,
        qos_aggressor_share: float = 0.5,
        blackbox_enabled: bool = True,
        blackbox_interval: float = 5.0,
        blackbox_max_segments: int = 64,
        blackbox_max_bytes: int = 16 << 20,
        blackbox_keep_postmortems: int = 4,
        blackbox_history_window: float = 60.0,
    ):
        self.host = host
        # HBM budget override: device memory is process-global (one
        # accelerator per process), so this reconfigures the singleton
        # cap — last-configured node wins in multi-node test processes.
        # None keeps the probed/env default (membudget.default_budget).
        if device_budget is not None:
            from pilosa_tpu.core import membudget

            membudget.configure(device_budget)
        self.tls = bool(tls_cert)
        # Cluster-on-mesh: advertise this node's holder in the process
        # placement map on start() so in-process peers (one process per
        # mesh) answer our shards with a jit-sharded launch instead of an
        # HTTP relay; see parallel/meshplace.py and docs/serving.md.
        # False keeps the node off the mesh in BOTH directions: it never
        # registers, and its own fan-outs stay on the HTTP relay.
        self.mesh_dispatch = mesh_dispatch
        self.holder = Holder(n_words)
        # Metrics backend; MemStatsClient serves /metrics + /debug/vars
        # (reference server.go:397-411 metric.service selection).
        from pilosa_tpu.obs.stats import MemStatsClient

        self.holder.set_stats(
            stats_client if stats_client is not None else MemStatsClient()
        )
        # SLO-plane knobs: override the holder's default tracker when any
        # is set (tests/load harness shrink windows so burn behavior is
        # observable in seconds, not days).
        if (
            slo_objectives is not None
            or slo_burn_rules is not None
            or slo_slot_seconds is not None
            or slo_latency_window is not None
        ):
            from pilosa_tpu.obs import slo as slo_mod

            rules = None
            if slo_burn_rules is not None:
                rules = tuple(
                    slo_mod.BurnRule(
                        r["name"], r["long"], r["short"], r["factor"]
                    )
                    for r in slo_burn_rules
                )
            self.holder.slo = slo_mod.SLOTracker(
                objectives=(
                    slo_mod.objectives_from_dict(slo_objectives)
                    if slo_objectives is not None
                    else None
                ),
                burn_rules=rules,
                slot_seconds=(
                    slo_slot_seconds if slo_slot_seconds is not None else 5.0
                ),
                latency_window=(
                    slo_latency_window
                    if slo_latency_window is not None
                    else 300.0
                ),
            )
            # re-point the trace store at the replacement tracker (its
            # slow-keep thresholds + exemplar sink live there)
            self.holder.traces.slo = self.holder.slo
            self.holder.traces.on_keep = self.holder.slo.attach_exemplar
        self.holder.traces.capacity = max(1, int(trace_store_capacity))
        self.holder.traces.baseline_n = int(trace_baseline_n)
        self.store = None
        if data_dir is not None:
            self.store = HolderStore(self.holder, data_dir)
            self.store.open()
        node_id = self.store.node_id() if self.store else uuid.uuid4().hex
        # Event journal / job tracker / trace store carry this node's id
        # on every record (the cluster merges key on it).
        self.holder.events.node_id = node_id
        self.holder.jobs.node_id = node_id
        self.holder.traces.node_id = node_id
        self.cluster = Cluster(node_id, replica_n=replica_n, disabled=True)
        # Every cluster-state transition — local or applied from a peer's
        # broadcast — lands on the timeline.
        self.cluster.on_state_change = (
            lambda state: self.holder.events.record(
                ev.EVENT_CLUSTER_STATE, state=state
            )
        )
        self.client = InternalClient(
            timeout=client_timeout,
            skip_verify=tls_skip_verify,
            ca_cert=tls_ca_cert,
            stats=self.holder.stats,
            retry_budget=client_retry_budget,
            breaker_threshold=breaker_threshold,
            breaker_cooldown=breaker_cooldown,
            # Deterministic jitter per node (chaos tests rely on replay).
            rng_seed=zlib.crc32(node_id.encode()),
            journal=self.holder.events,
        )
        self.broadcaster = HTTPBroadcaster(self.cluster, self.client, node_id)
        self.api = API(
            self.holder,
            self.store,
            cluster=self.cluster,
            client=self.client,
            broadcaster=self.broadcaster,
            import_workers=import_workers,
            import_queue_depth=import_queue_depth,
            ingest_staging_buffers=ingest_staging_buffers,
            ingest_upload_slots=ingest_upload_slots,
            max_writes_per_request=max_writes_per_request,
            batch_window=batch_window,
            batch_max_size=batch_max_size,
            rescache_entries=rescache_entries,
            rescache_promote_hits=rescache_promote_hits,
            rescache_demote_deltas=rescache_demote_deltas,
            planner_enabled=planner_enabled,
            qos_enabled=qos_enabled,
            qos_weights=qos_weights,
            qos_down_factor=qos_down_factor,
            qos_stage_hold=qos_stage_hold,
            qos_relax_hold=qos_relax_hold,
            qos_tick_interval=qos_tick_interval,
            qos_retry_after=qos_retry_after,
            qos_aggressor_share=qos_aggressor_share,
        )
        self._wire_shard_broadcasts()
        # Route new-key allocation to the translation primary (reference
        # translate.go:91-97); collapses to the local store standalone.
        from pilosa_tpu.cluster.translate_proxy import PrimaryTranslateStore

        proxy = PrimaryTranslateStore(
            self.api.executor.translator, self.cluster, self.client
        )
        self.api.executor.translator = proxy
        if self.api.dist is not None:
            self.api.dist.local.translator = proxy
        self.server = Server(
            self.api,
            host=host,
            port=port,
            long_query_time=long_query_time,
            tls_cert=tls_cert,
            tls_key=tls_key,
            default_deadline=default_deadline,
            slow_query_time=slow_query_time,
        )
        # Diagnostics + runtime metrics loops (reference server.go:433-436
        # monitorDiagnostics/monitorRuntime, gcnotify).
        from pilosa_tpu import __version__
        from pilosa_tpu.obs.diagnostics import Diagnostics
        from pilosa_tpu.obs.sysinfo import GCNotifier, RuntimeMonitor

        self.diagnostics = Diagnostics(
            self.holder, self.cluster, version=__version__
        )
        self.api.diagnostics = self.diagnostics
        # Flight recorder + incident engine (obs/flightrec.py): always-on
        # segment ring, SLO-alert/504-spike auto-capture at
        # /debug/incidents.  start()/stop() ride the node lifecycle.
        self.flightrec = None
        if flight_recorder:
            from pilosa_tpu.obs.flightrec import FlightRecorder

            self.flightrec = FlightRecorder(
                self.holder,
                api=self.api,
                client=self.client,
                segment_seconds=flightrec_segment_seconds,
                sample_interval=flightrec_sample_interval,
                segments=flightrec_segments,
                spike_504=flightrec_spike_504,
            )
            self.api.flightrec = self.flightrec
        # Retrospective metrics plane (obs/history.py): ring-buffer TSDB
        # sampled at ~1 s cadence + EWMA trend detectors that promote
        # sustained latency/throughput/error anomalies into `trend`
        # flight-recorder incidents carrying their own series windows.
        self.history = None
        if history_enabled:
            from pilosa_tpu.obs.history import MetricsHistory

            self.history = MetricsHistory(
                self.holder,
                api=self.api,
                node_id=self.node_id,
                cadence=history_cadence,
                tiers=history_tiers,
                detectors=history_detectors,
                warmup=history_warmup,
                trips=history_trips,
                latency_factor=history_latency_factor,
                latency_min_ms=history_latency_min_ms,
            )
            self.api.history = self.history
            if self.flightrec is not None:
                self.history.flightrec = self.flightrec
                self.flightrec.series_provider = (
                    self.history.incident_series
                )
        # Device cost ledger: recompile-storm detection (>= threshold new
        # XLA compiles inside the window, once past warmup) freezes a
        # flight-recorder incident bundle naming the storming sites and
        # shapes.  The ledger is process-global; the last-configured node
        # wins in multi-node test processes (same rule as device_budget).
        from pilosa_tpu.obs import devledger

        devledger.configure_storm(
            threshold=devledger_storm_threshold,
            window_s=devledger_storm_window,
            warmup_s=devledger_warmup,
        )
        if self.flightrec is not None:
            devledger.on_storm(self.flightrec.capture_incident)
        # Crash-durable black box (obs/blackbox.py): a bounded on-disk
        # spool continuously checkpointing the perishable tails of the
        # planes above; on a dirty restart the previous life's spool is
        # sealed into the postmortem served at /debug/postmortem.  Only
        # meaningful with a data dir — a diskless node has nowhere to
        # survive a crash.
        self.blackbox = None
        self.postmortem = None
        if blackbox_enabled and data_dir is not None:
            from pilosa_tpu.obs.blackbox import BlackBox

            self.blackbox = BlackBox(
                self.holder,
                data_dir,
                api=self.api,
                flightrec=self.flightrec,
                history=self.history,
                node_id=self.node_id,
                interval=blackbox_interval,
                max_segments=blackbox_max_segments,
                max_bytes=blackbox_max_bytes,
                keep_postmortems=blackbox_keep_postmortems,
                history_window=blackbox_history_window,
            )
            self.api.blackbox = self.blackbox
            self.postmortem = self.blackbox.open()
            if self.flightrec is not None:
                # incident bundles reach disk the moment they freeze,
                # not up to one writer interval later
                self.flightrec.on_incident = self.blackbox.flush_incident
        self._stopped = False
        self.gc_notifier = GCNotifier()
        self.runtime_monitor = RuntimeMonitor(
            self.holder.stats,
            interval=metric_poll_interval,
            gc_notifier=self.gc_notifier,
        )
        self.membership = None  # started on demand via start_membership()
        self._ae_loop = None  # anti-entropy loop (start_anti_entropy)
        # Stuck-resize backstop (0 disables — single-node tests don't
        # need the thread).
        self.resize_watchdog = None
        if resize_watchdog_deadline > 0:
            self.resize_watchdog = ResizeWatchdog(
                self, deadline=resize_watchdog_deadline
            )

    # -- shard availability broadcasts (reference view.go:239-261
    #    CreateShardMessage) ------------------------------------------------

    def _wire_shard_broadcasts(self) -> None:
        """Chain a create-shard broadcast after any existing (storage)
        fragment-creation hook so peers learn shard availability."""

        def wire_field(idx, field):
            prev = field.on_create_fragment

            def on_fragment(view, shard, _prev=prev, _index=idx.name, _field=field.name):
                if _prev is not None:
                    _prev(view, shard)
                self._broadcast_shard(_index, _field, shard)

            field.on_create_fragment = on_fragment
            for view in field.views.values():
                view.on_create_fragment = on_fragment

        def wire_index(idx):
            prev = idx.on_create_field

            def on_field(idx2, field, _prev=prev):
                if _prev is not None:
                    _prev(idx2, field)
                wire_field(idx2, field)

            idx.on_create_field = on_field
            for f in list(idx.fields.values()):
                wire_field(idx, f)

        prev_idx = self.holder.on_create_index

        def on_index(idx, _prev=prev_idx):
            if _prev is not None:
                _prev(idx)
            wire_index(idx)

        self.holder.on_create_index = on_index
        for idx in list(self.holder.indexes.values()):
            wire_index(idx)

    def _broadcast_shard(self, index: str, field: str, shard: int) -> None:
        if len(self.cluster.nodes) <= 1:
            return
        try:
            self.broadcaster.send_sync(
                {
                    "type": bc.MSG_CREATE_SHARD,
                    "index": index,
                    "field": field,
                    "shard": shard,
                }
            )
        except Exception:
            # Shard availability re-converges via node status exchange;
            # a failed advisory broadcast must not fail the write path.
            self.holder.stats.count("broadcast_errors", 1)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self.server.serve_background()
        self.cluster.local_node.uri = self.uri
        from pilosa_tpu.parallel import meshplace

        if self.mesh_dispatch and meshplace.enabled():
            meshplace.default_placement().register(self.node_id, self.holder)
        elif self.api.dist is not None:
            self.api.dist.mesh_enabled = False
        self.runtime_monitor.start()
        if self.flightrec is not None:
            self.flightrec.start()
        if self.history is not None:
            self.history.start()
        if self.resize_watchdog is not None:
            self.resize_watchdog.start()
        if self.blackbox is not None:
            self.blackbox.start()
        self.holder.events.record(
            ev.EVENT_NODE_START, uri=self.uri, state=self.api.state
        )

    def start_anti_entropy(self, interval: float) -> None:
        """Background anti-entropy loop (reference server.go:494-546
        monitorAntiEntropy): one sync_holder pass per interval — block
        checksum repair between replicas AND the translate-log
        replication pull (translate_proxy.sync_from_primary rides this
        carrier).  Runs even at replica_n=1 (translation still
        replicates to non-primaries) and keeps running in DEGRADED
        (repair between survivors matters most then); only
        RESIZING/STARTING skip.  Idempotent; stop() ends it."""
        from pilosa_tpu.cluster.antientropy import AntiEntropyLoop

        if interval <= 0:
            return
        old = self._ae_loop
        if old is not None:
            if old._thread is not None and old._thread.is_alive():
                return  # already running (or a stopped pass still
                # draining — must not overlap two passes)
            self._ae_loop = None  # fully exited: re-arm below
        self._ae_loop = AntiEntropyLoop(
            self.syncer(), interval, state_fn=lambda: self.api.state
        )
        self._ae_loop.start()

    @property
    def uri(self) -> str:
        scheme = "https" if self.tls else "http"
        return f"{scheme}://{self.host}:{self.server.port}"

    @property
    def node_id(self) -> str:
        return self.cluster.node_id

    def resize_coordinator(self):
        """Resize entry point; valid only on the coordinator (reference
        cluster.go:1171 unprotectedGenerateResizeJob)."""
        from pilosa_tpu.cluster.resize import ResizeCoordinator, ResizeError

        if not self.cluster.is_coordinator:
            raise ResizeError("resize must run on the coordinator")
        return ResizeCoordinator(self.cluster, self.client, self.api)

    def syncer(self):
        """Anti-entropy syncer for this node (reference holderSyncer)."""
        from pilosa_tpu.cluster.antientropy import HolderSyncer

        return HolderSyncer(self.holder, self.cluster, self.client, self.api)

    def join_static(self, members: list[tuple[str, str]], coordinator_id: str) -> None:
        """Fix cluster membership (reference cluster.go:2000 setStatic).
        ``members`` is [(node_id, uri), ...] including this node.

        Joining also performs the state HANDSHAKE: the coordinator's
        NodeStatus — schema plus available-shard bitmaps — is pulled and
        applied immediately, so a (re)started node answers
        schema-dependent queries correctly BEFORE the first anti-entropy
        pass (the reference exchanges full NodeStatus on every
        memberlist push/pull sync, gossip.go:321-357).  Best-effort: at
        initial cluster formation the coordinator may not be up yet, and
        anti-entropy remains the healer of record."""
        self.cluster.coordinator_id = coordinator_id
        self.cluster.disabled = False
        self.cluster.set_static([Node(id=i, uri=u) for i, u in members])
        self.holder.events.record(
            ev.EVENT_MEMBERSHIP_SET,
            members=[i for i, _ in members],
            coordinator=coordinator_id,
        )
        if coordinator_id == self.cluster.node_id:
            return
        coord = next(
            (n for n in self.cluster.nodes if n.id == coordinator_id), None
        )
        if coord is None or not coord.uri:
            return
        try:
            status = self.client.status(coord.uri)
        except Exception as e:
            logger.warning(
                "join handshake with coordinator %s failed (anti-entropy"
                " will converge): %s", coordinator_id, e,
            )
            return
        schema = status.get("schema")
        if schema:
            try:
                self.holder.apply_schema(schema)
            except Exception as e:
                logger.warning("join handshake schema apply failed: %s", e)
        if status.get("availableShards"):
            self.api.merge_available_shards(status["availableShards"])

    def start_membership(
        self, probe_interval: float = 1.0, confirm_retries: int = 10,
        confirm_interval: float = 0.1,
    ) -> "MembershipMonitor":
        """Begin heartbeat failure detection over the current membership
        (reference gossip probes + confirmNodeDown, cluster.go:1699-1768)."""
        from pilosa_tpu.cluster.membership import MembershipMonitor

        if self.membership is None:
            self.membership = MembershipMonitor(
                self.cluster,
                self.client,
                broadcaster=self.broadcaster,
                probe_interval=probe_interval,
                confirm_retries=confirm_retries,
                confirm_interval=confirm_interval,
                journal=self.holder.events,
            )
            self.membership.start()
        return self.membership

    def shutdown_graceful(self) -> None:
        """The orderly SIGTERM path: journal ``node-stop`` (so the
        black box's final checkpoint carries it), then run the full
        stop — drain the batcher/QoS queues, stop the samplers, write
        the clean-shutdown marker.  Callers (signal handler, CLI) exit
        0 afterwards: a graceful stop must never read as a crash."""
        if self._stopped:
            return
        self.holder.events.record(ev.EVENT_NODE_STOP, uri=self.uri)
        self.stop()

    def install_signal_handlers(self) -> bool:
        """Route SIGTERM through :meth:`shutdown_graceful` for this
        node.  Returns False off the main thread (in-process test
        clusters manage lifecycle themselves)."""
        from pilosa_tpu.obs import blackbox as bb

        return bb.install_signal_handlers(self)

    def stop(self) -> None:
        if self._stopped:
            return  # SIGTERM handler + CLI finally may both land here
        self._stopped = True
        from pilosa_tpu.obs import blackbox as bb
        from pilosa_tpu.parallel import meshplace

        bb.uninstall_signal_handlers(self)
        # Withdraw from the placement map FIRST: peers must stop
        # resolving our fragments before the holder starts tearing down.
        meshplace.default_placement().unregister(self.node_id)
        if self._ae_loop is not None:
            # the loop reference is kept even if a slow pass outlives the
            # join timeout, so a restart can't spawn a second loop while
            # the old pass is still running
            self._ae_loop.stop()
        if self.membership is not None:
            self.membership.stop()
        if self.api.dist is not None:
            self.api.dist.close()
        if self.resize_watchdog is not None:
            self.resize_watchdog.stop()
        if self.history is not None:
            self.history.stop()
        if self.flightrec is not None:
            self.flightrec.stop()
        self.runtime_monitor.stop()
        self.diagnostics.stop()
        self.gc_notifier.close()
        self.server.close()
        if self.blackbox is not None:
            # last: the final checkpoint captures the drained planes,
            # then the clean marker seals this life as orderly
            self.blackbox.close(clean=True)
