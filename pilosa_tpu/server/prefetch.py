"""Flight-driven predictive prefetch: stage the next flight's device
assets while the previous one computes.

The batcher's admission queue is an oracle the storage tier never had:
at window close (and at every submit) the full (index, query, shards)
set of an upcoming flight is known before any kernel launches.  This
module resolves that set to the *field stacks* the batched dispatch will
consume (exec/executor.py ``_field_stack`` — the serving tier's
device-resident unit; per-call reads answer from host mirrors), filters
to the ones not currently cached, and rides them onto the ingest
``DeviceUploader``'s low-priority queue (ingest/pipeline.py) — the H2D
build overlaps the in-flight dispatch instead of stalling the next one.
Everything here is advisory and bounded:

* resolution never takes a stack lock (the ``_stack_cached`` peek is
  racy by design; a stale read costs at most a wasted, booked build);
* fully-resident processes skip the whole path (a budget with no cap
  can never evict, so there is nothing to predict);
* a busy uploader drops prefetches rather than queueing unboundedly —
  the dispatch then pays its own build, exactly the pre-prefetch
  behavior.

Accounting flows through core/residency.py: issued at submit, useful on
the first query hit against a prefetch-built stack (the lane-level bar
is useful/issued >= 0.5, bench.py residency lane).
"""

from __future__ import annotations

import time

from pilosa_tpu.core import membudget, residency
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.obs import qprofile

# Per-flight ceiling: a pathological flight (hundreds of distinct
# fields) must not convert the prefetch queue into a full index crawl;
# beyond this the tail pays cold builds as before.
MAX_TARGETS_PER_FLIGHT = 32

# Once a stack is staged, don't re-issue it for this long: the uploader
# dedups keys while they sit in its queue, but between dequeue and the
# build landing in the cache the racy ``_stack_cached`` peek reads cold
# and a burst would book one issued-but-wasted build per submit.  Kept
# short — it only needs to cover that dequeue->landed gap; anything
# longer blocks legitimate RE-staging after the budget evicts the stack
# (under heavy oversubscription that demotes warm-tail queries to the
# per-call fallback path for the whole suppression window).
REISSUE_TTL = 0.05  # seconds


def fields_of_query(query) -> set[str]:
    """Field names a parsed PQL query can touch, from the call tree:
    ``Row(f=1)``-style field args, explicit ``_field``/``field`` args,
    and every nested call (children and call-valued args)."""
    names: set[str] = set()

    def walk(call):
        f = call.args.get("_field")
        if isinstance(f, str):
            names.add(f)
        f = call.args.get("field")
        if isinstance(f, str):
            names.add(f)
        fa = call.field_arg()
        if fa is not None:
            names.add(fa)
        for v in call.args.values():
            if hasattr(v, "args") and hasattr(v, "children"):
                walk(v)
        for c in call.children:
            walk(c)

    for call in query.calls:
        walk(call)
    return names


class _StackTarget:
    """Uploadable wrapper: quacks like a fragment for the DeviceUploader
    (``device_bits`` = build the stack; ``prefetch_key`` = stable dedup
    identity across flights)."""

    __slots__ = ("executor", "field", "shards", "view", "prefetch_key")

    def __init__(self, executor, field, shards, view):
        self.executor = executor
        self.field = field
        self.shards = shards
        self.view = view
        self.prefetch_key = (id(field), tuple(shards), view)

    def device_bits(self):
        self.executor.prefetch_stack(self.field, self.shards, self.view)


def stack_pairs_of_query(idx, query) -> list[tuple[str, str]]:
    """The distinct (field, view) stack pairs the batched dispatch would
    demand for this query — resolved with the *same* matcher
    ``_batch_general`` compiles with (exec/astbatch.py), so the
    prediction is exact: a bare ``Count(Row)`` (segment path, host-side)
    stages nothing, while a ``Count(Intersect(...))`` stages every leaf
    view including time-range covers and the Not existence row."""
    from pilosa_tpu.exec import astbatch

    out: list[tuple[str, str]] = []
    for call in query.calls:
        leaves: list = []
        pairs: list[tuple[str, str]] = []
        if astbatch.match_count(idx, call, leaves, pairs) is None:
            if call.name not in (
                "Intersect", "Union", "Difference", "Xor", "Not",
            ):
                continue
            leaves, pairs = [], []
            if astbatch.match_tree(idx, call, leaves, pairs) is None:
                continue
        for pair in pairs:
            if pair not in out:
                out.append(pair)
    return out


class FlightPrefetcher:
    """Resolves flights to not-yet-resident field stacks and stages them
    on the shared DeviceUploader (ingest keeps strict priority)."""

    def __init__(
        self,
        holder,
        uploader,
        executor,
        max_per_flight: int = MAX_TARGETS_PER_FLIGHT,
    ):
        self.holder = holder
        self.uploader = uploader
        self.executor = executor
        self.max_per_flight = max_per_flight
        self.flights = 0  # flights that issued at least one prefetch
        # prefetch_key -> monotonic issue time (REISSUE_TTL suppression);
        # touched only from submit/dispatch threads under no lock — a
        # lost update just re-issues one prefetch
        self._recent: dict[tuple, float] = {}

    def _candidates(self, index: str, query, shards):
        idx = self.holder.index(index)
        if idx is None:
            return
        if shards is None:
            shard_list = sorted(idx.available_shards())
        else:
            shard_list = sorted(shards)
        if not shard_list:
            return
        for fname, vname in stack_pairs_of_query(idx, query):
            field = idx.field(fname)
            if field is None or field.view(vname) is None:
                continue
            # racy peek by design: a stale read costs one wasted build
            if self.executor._stack_cached(field, shard_list, vname):
                continue
            yield _StackTarget(self.executor, field, shard_list, vname)

    def prefetch_flight(self, flights) -> int:
        """Stage every not-yet-cached stack the flight set will touch;
        returns the number of prefetches actually queued.  Must never
        raise into the serving path."""
        budget = membudget.default_budget()
        if budget.cap is None:
            return 0  # nothing can be evicted; nothing to predict
        tracker = residency.default_tracker()
        t0 = time.perf_counter()
        now = time.monotonic()
        issued = 0
        seen: set[tuple] = set()
        try:
            for index, query, shards in flights:
                for target in self._candidates(index, query, shards):
                    if target.prefetch_key in seen:
                        continue
                    seen.add(target.prefetch_key)
                    if now - self._recent.get(target.prefetch_key, -1e9) < REISSUE_TTL:
                        continue  # staged moments ago; let it land
                    if issued >= self.max_per_flight:
                        tracker.note_prefetch_dropped()
                        continue
                    if self.uploader.submit_prefetch(target, self._done):
                        issued += 1
                        tracker.note_prefetch_issued()
                        self._recent[target.prefetch_key] = now
                        if len(self._recent) > 4096:
                            self._recent = {
                                k: t
                                for k, t in self._recent.items()
                                if now - t < REISSUE_TTL
                            }
                    else:
                        tracker.note_prefetch_dropped()
        except Exception:
            tracker.note_prefetch_error()
            return issued
        if issued:
            self.flights += 1
            qprofile.annotate(
                "residency.prefetch",
                duration_ms=(time.perf_counter() - t0) * 1e3,
                issued=issued,
            )
        return issued

    def prefetch_query(self, index: str, query, shards) -> int:
        """Submit-time staging for one query (handler thread): overlaps
        the build with whatever flight is currently dispatching."""
        return self.prefetch_flight([(index, query, shards)])

    def _done(self, target, err) -> None:
        if err is not None:
            residency.default_tracker().note_prefetch_error()

    def snapshot(self) -> dict:
        return {
            "flights": self.flights,
            "maxPerFlight": self.max_per_flight,
        }
