"""Cost-governed multi-tenant QoS: weighted-fair admission, load
shedding, and degraded serving tiers.

The PR 6 batcher admitted strictly FIFO: one hot tenant could occupy
every slot of every flight while the devledger (obs/devledger.py)
dutifully *measured* the damage and the SLO tracker (obs/slo.py)
*recorded* the burn — nothing closed the loop.  This module is the
controller: classic weighted-fair queueing in virtual time (WFQ/DRF,
the same family as the iteration-level admission schedulers the
batcher docstring cites), with tenant debt debited by MEASURED
per-tenant device-ms from the ledger rather than by query counts.

Scheduling — per-tenant virtual-time queues:

* every tenant carries a virtual start time ``vstart``; the scheduler
  always pops the tenant with the least ``vstart`` (global arrival
  sequence breaks ties, so equal-debt tenants stay FIFO);
* popping charges the tenant's estimated per-query device cost divided
  by its effective weight — cheap tenants interleave tightly, a tenant
  whose queries each burn milliseconds of device time falls behind in
  virtual time and yields slots;
* cost estimates are reconciled from the devledger on every governor
  tick: measured device-ms deltas per tenant, divided by the queries
  served since the last tick.  Debt accounting is EXACT — every
  measured millisecond lands in some tenant's ``debt_ms`` (the
  conservation property tests/test_qos.py holds the governor to);
* a tenant going idle re-enters at ``max(vstart, vtime)``: sleeping
  never banks credit (the standard WFQ catch-up rule).

Pressure ladder — three stages per tenant, driven by SLO pressure
(burn alerts firing or latency objectives violated) and the ledger's
view of who is paying for it:

1. **deprioritize** — the aggressor's effective weight is divided by
   ``down_factor``; it still runs, behind everyone else;
2. **degrade** — the aggressor's TopN/GroupBy queries are served from
   maintained views / last-known semantic-cache entries
   (exec/rescache.py ``lookup_stale``), explicitly marked
   ``"degraded": true`` in the response envelope;
3. **shed** — admission raises :class:`ShedError`, which the HTTP
   layer maps to ``429`` with a ``Retry-After`` header.  Never a
   silent 504: shed responses are attributed, counted per tenant, and
   do not burn the tenant's error budget (4xx are client-visible
   backpressure, not server failures).

An "aggressor" is only ever named when at least two tenants are
active and one of them owns a dominant share (``aggressor_share``) of
the measured device-ms rate — a single-tenant node under load is slow,
not abusive, and the ladder stays out of the way.

Every transition is journaled (obs/events.py) and surfaces in
``/debug/qos``; the FIRST escalation of a pressure episode captures
exactly one flight-recorder incident (obs/flightrec.py
``capture_incident``), so an overload shows up as one triageable
bundle rather than an incident per tick.
"""

from __future__ import annotations

import contextvars
import queue
import threading
import time
from collections import deque

from pilosa_tpu.obs import devledger

ADMIT = "admit"
DEGRADE = "degrade"

_MAX_TENANTS = 128  # governor state rows; beyond this, new tenants fold
_OVERFLOW_TENANT = "~overflow"
_MAX_TRANSITIONS = 32  # recent ladder transitions kept for /debug/qos

_STAGE_NAMES = ("normal", "deprioritized", "degraded", "shedding")


class ShedError(Exception):
    """Admission refused under stage-3 pressure; HTTP maps this to
    429 + Retry-After (server/http.py) — never a silent 504."""

    def __init__(self, tenant: str, retry_after: float):
        super().__init__(
            f"tenant {tenant!r} is being shed under device pressure; "
            f"retry after {retry_after:g}s"
        )
        self.tenant = tenant
        self.retry_after = float(retry_after)


# Request-scoped marker: the batcher sets it when a query was served
# from the degraded tier; API.query() takes it and stamps the response
# envelope (same note/take pattern as obs/slo.py note_class).
_degraded: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "pilosa_qos_degraded", default=False
)


def note_degraded() -> None:
    _degraded.set(True)


def take_degraded() -> bool:
    served = _degraded.get()
    if served:
        _degraded.set(False)
    return served


class _TenantState:
    """Per-tenant scheduler + ladder state (all mutation under the
    governor's condition lock)."""

    __slots__ = (
        "name", "weight", "stage", "stage_since", "vstart", "queue",
        "admitted", "served", "shed", "degraded", "debt_ms", "cost_est",
        "rate_ewma", "served_since_debit", "last_active",
        "admits_since_tick", "admits_last_tick", "admit_ewma",
    )

    def __init__(self, name: str, weight: float, now: float):
        self.name = name
        self.weight = max(float(weight), 1e-6)
        self.stage = 0
        self.stage_since = now
        self.vstart = 0.0
        self.queue: deque = deque()  # (seq, flight) arrival order
        self.admitted = 0  # admission decisions that let the query in
        self.served = 0  # flights actually popped by the dispatcher
        self.shed = 0  # 429s issued
        self.degraded = 0  # queries served from the degraded tier
        self.debt_ms = 0.0  # cumulative MEASURED device-ms (ledger)
        self.cost_est = 1.0  # EWMA device-ms per served query
        self.rate_ewma = 0.0  # EWMA device-ms per governor tick
        self.served_since_debit = 0
        self.last_active = now
        self.admits_since_tick = 0  # admission ATTEMPTS (incl. shed)
        self.admits_last_tick = 0  # attempts seen by the previous tick
        self.admit_ewma = 0.0  # EWMA attempts per governor tick

    def offered_load(self) -> float:
        """Estimated device-ms per tick this tenant is ASKING for:
        admission-attempt rate times the per-query cost estimate.
        Attempt-based on purpose — measured device-ms collapses the
        moment a tenant is deprioritized or shed, which would exonerate
        the aggressor mid-episode; a flooding client keeps attempting
        and so keeps owning the pressure."""
        return self.admit_ewma * max(self.cost_est, 1e-3)

    def effective_weight(self, down_factor: float) -> float:
        if self.stage <= 0:
            return self.weight
        return self.weight / (down_factor ** min(self.stage, 2))


class QosGovernor:
    """Weighted-fair admission queue + pressure-ladder controller.

    Doubles as the batcher's queue object: :meth:`put`/:meth:`get`/
    :meth:`empty` present the ``queue.Queue`` surface the dispatcher
    loop expects (including re-raising ``queue.Empty`` on timeout and
    replaying the batcher's stop sentinel once the queues drain, which
    preserves close()'s drain-then-exit contract).
    """

    def __init__(
        self,
        stats=None,
        weights: dict | None = None,
        enabled: bool = True,
        down_factor: float = 8.0,
        stage_hold: float = 2.0,
        relax_hold: float = 5.0,
        tick_interval: float = 0.25,
        retry_after: float = 1.0,
        aggressor_share: float = 0.5,
        active_window: float = 10.0,
        slo_fn=None,
        ledger_fn=None,
        journal_fn=None,
        incident_fn=None,
    ):
        self.stats = stats if hasattr(stats, "count_with_tags") else None
        self.enabled = bool(enabled)
        self.down_factor = max(float(down_factor), 1.0)
        self.stage_hold = float(stage_hold)
        self.relax_hold = float(relax_hold)
        self.tick_interval = float(tick_interval)
        self.retry_after = max(float(retry_after), 0.0)
        self.aggressor_share = float(aggressor_share)
        self.active_window = float(active_window)
        # Control-loop taps, injected late (NodeServer installs the
        # flight recorder after API construction): callables so the
        # governor never holds a stale reference.
        self._slo_fn = slo_fn  # () -> SLOTracker | None
        self._ledger_fn = ledger_fn  # () -> {tenant: {"deviceMs": ...}}
        self._journal_fn = journal_fn  # () -> EventJournal | None
        self._incident_fn = incident_fn  # (trigger: dict) -> None
        self._cond = threading.Condition()
        self._tenants: dict[str, _TenantState] = {}
        self._weights = dict(weights or {})
        self._vtime = 0.0
        self._seq = 0
        self._stop = None  # batcher's stop sentinel, replayed at drain
        self._last_tick = time.monotonic()
        self._ledger_last: dict[str, float] = {}
        self._episode_active = False
        self.episodes = 0
        self._transitions: deque = deque(maxlen=_MAX_TRANSITIONS)

    # -- tenant state ---------------------------------------------------------

    def _state_locked(self, tenant: str, now: float) -> _TenantState:
        ts = self._tenants.get(tenant)
        if ts is None:
            if len(self._tenants) >= _MAX_TENANTS:
                tenant = _OVERFLOW_TENANT
                ts = self._tenants.get(tenant)
                if ts is not None:
                    return ts
            ts = _TenantState(
                tenant, self._weights.get(tenant, 1.0), now
            )
            self._tenants[tenant] = ts
        return ts

    @staticmethod
    def _tenant_of(item) -> str:
        principal = getattr(item, "principal", None)
        if principal:
            return principal[0] or devledger.DEFAULT_TENANT
        return devledger.DEFAULT_TENANT

    # -- admission ------------------------------------------------------------

    def admit(self, tenant: str | None, can_degrade: bool = False) -> str:
        """Admission decision for one query.  Returns :data:`ADMIT` or
        :data:`DEGRADE`; raises :class:`ShedError` at stage 3."""
        tenant = tenant or devledger.DEFAULT_TENANT
        self.maybe_tick()
        now = time.monotonic()
        shed_exc = None
        counter = None
        with self._cond:
            ts = self._state_locked(tenant, now)
            ts.last_active = now
            ts.admits_since_tick += 1
            if self.enabled and ts.stage >= 3:
                ts.shed += 1
                counter = ("qos_shed", ts.name)
                shed_exc = ShedError(ts.name, self.retry_after)
            else:
                ts.admitted += 1
                counter = ("qos_admitted", ts.name)
                decision = (
                    DEGRADE
                    if self.enabled and ts.stage >= 2 and can_degrade
                    else ADMIT
                )
        if self.stats is not None:
            self.stats.count_with_tags(
                counter[0], 1, 1.0, (f"tenant:{counter[1]}",)
            )
        if shed_exc is not None:
            raise shed_exc
        return decision

    def note_degraded_served(self, tenant: str | None) -> None:
        tenant = tenant or devledger.DEFAULT_TENANT
        with self._cond:
            ts = self._state_locked(tenant, time.monotonic())
            ts.degraded += 1
        if self.stats is not None:
            self.stats.count_with_tags(
                "qos_degraded", 1, 1.0, (f"tenant:{tenant}",)
            )

    # -- queue surface (the batcher's dispatcher loop) ------------------------

    def put(self, item) -> None:
        """Enqueue a flight under its tenant's virtual-time queue.  An
        object without a ``principal`` is the batcher's stop sentinel:
        it is replayed by :meth:`get` only once every queue drains."""
        now = time.monotonic()
        if getattr(item, "principal", None) is None:
            with self._cond:
                self._stop = item
                self._cond.notify_all()
            return
        tenant = self._tenant_of(item)
        with self._cond:
            ts = self._state_locked(tenant, now)
            ts.last_active = now
            if not ts.queue:
                # idle catch-up: a sleeping tenant never banks credit
                ts.vstart = max(ts.vstart, self._vtime)
            self._seq += 1
            ts.queue.append((self._seq, item))
            self._cond.notify()

    def get(self, timeout: float | None = None):
        """Pop the flight with the least virtual start time; block like
        ``queue.Queue.get`` (raising ``queue.Empty`` on timeout)."""
        limit = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                best = None
                for ts in self._tenants.values():
                    if not ts.queue:
                        continue
                    key = (ts.vstart, ts.queue[0][0])
                    if best is None or key < best[0]:
                        best = (key, ts)
                if best is not None:
                    ts = best[1]
                    _, item = ts.queue.popleft()
                    # advance virtual time: charge the tenant's current
                    # cost estimate against its effective weight
                    self._vtime = ts.vstart
                    ts.vstart += max(ts.cost_est, 1e-3) / ts.effective_weight(
                        self.down_factor
                    )
                    ts.served += 1
                    ts.served_since_debit += 1
                    return item
                if self._stop is not None:
                    return self._stop
                if timeout is None:
                    self._cond.wait()
                else:
                    rem = limit - time.monotonic()
                    if rem <= 0:
                        raise queue.Empty
                    self._cond.wait(rem)

    def empty(self) -> bool:
        with self._cond:
            return self._stop is None and not any(
                ts.queue for ts in self._tenants.values()
            )

    def depth(self) -> int:
        with self._cond:
            return sum(len(ts.queue) for ts in self._tenants.values())

    # -- debt: measured device-ms from the ledger -----------------------------

    def observe_ledger(self, tenant_ms: dict) -> float:
        """Debit each tenant's debt by its measured device-ms delta and
        reconcile the per-query cost estimate.  Returns the total
        milliseconds debited (conservation: every measured ms lands in
        exactly one tenant's ``debt_ms``)."""
        now = time.monotonic()
        total = 0.0
        with self._cond:
            for tenant, ms in tenant_ms.items():
                ms = float(ms)
                if ms <= 0:
                    continue
                ts = self._state_locked(tenant, now)
                ts.debt_ms += ms
                ts.last_active = now
                total += ms
                if ts.served_since_debit > 0:
                    per = ms / ts.served_since_debit
                    ts.cost_est = 0.7 * ts.cost_est + 0.3 * per
                    ts.served_since_debit = 0
            # decay every rate EWMA each observation so a tenant that
            # went quiet stops looking like the aggressor
            for ts in self._tenants.values():
                ts.rate_ewma = 0.5 * ts.rate_ewma + 0.5 * float(
                    tenant_ms.get(ts.name, 0.0) or 0.0
                )
        return total

    # -- pressure ladder ------------------------------------------------------

    def maybe_tick(self, now: float | None = None) -> None:
        """Run one control-loop tick if the interval elapsed.  Called
        from admission and dispatch paths — the governor has no thread
        of its own."""
        if now is None:
            now = time.monotonic()
        with self._cond:
            if now - self._last_tick < self.tick_interval:
                return
            self._last_tick = now
        self.tick(now)

    def _ledger_deltas(self) -> dict:
        if self._ledger_fn is None:
            return {}
        try:
            totals = self._ledger_fn() or {}
        except Exception:  # graftlint: disable=exception-hygiene -- a broken ledger tap must not take admission down; the ladder just sees zero deltas this tick
            return {}
        deltas = {}
        for tenant, row in totals.items():
            ms = float(row.get("deviceMs", 0.0)) if isinstance(row, dict) else float(row)
            prev = self._ledger_last.get(tenant, 0.0)
            if ms > prev:
                deltas[tenant] = ms - prev
            self._ledger_last[tenant] = ms
        return deltas

    def _under_pressure(self) -> bool:
        if self._slo_fn is None:
            return False
        try:
            tracker = self._slo_fn()
            pressure = tracker.pressure() if tracker is not None else None
        except Exception:  # graftlint: disable=exception-hygiene -- SLO tap failure degrades to "no pressure", never to a crashed dispatcher
            return False
        if not pressure:
            return False
        return bool(pressure.get("alerts") or pressure.get("latency"))

    def tick(self, now: float | None = None) -> list:
        """One ladder evaluation: debit ledger deltas, read SLO
        pressure, escalate the dominant aggressor or relax everyone.
        Returns the transitions it made (for tests)."""
        if now is None:
            now = time.monotonic()
        self.observe_ledger(self._ledger_deltas())
        pressure = self.enabled and self._under_pressure()
        transitions = []  # (tenant, old_stage, new_stage, reason)
        episode_started = False
        episode_ended = False
        incident = None
        with self._cond:
            for ts in self._tenants.values():
                ts.admits_last_tick = ts.admits_since_tick
                ts.admit_ewma = 0.5 * ts.admit_ewma + 0.5 * ts.admits_since_tick
                ts.admits_since_tick = 0
            # CONTENDERS are tenants that actually offered queries in
            # the last tick window (shed attempts count: a flooding
            # tenant stays a contender while its queries bounce).
            # Governance needs a live contest — two or more contenders
            # — not just recent activity: a decayed EWMA or a stale
            # last_active keeps the tenants of a FINISHED burst around
            # as ghosts for several ticks, and the sole live tenant of
            # the next workload phase would be designated aggressor
            # against nobody and shed.
            contenders = [
                ts
                for ts in self._tenants.values()
                if ts.admits_last_tick > 0
            ]
            if pressure and len(contenders) >= 2:
                # STICKY aggressor: a tenant already on the ladder stays
                # the episode's target as long as it keeps offering load.
                # Re-deriving the aggressor every tick would rotate the
                # ladder onto the victim the moment the real aggressor's
                # demand is suppressed — exactly the tenant the governor
                # exists to defend.  A designated tenant that genuinely
                # went quiet (admit_ewma ~ 0) releases the designation.
                elevated = [ts for ts in contenders if ts.stage > 0]
                if elevated:
                    aggressor = max(
                        elevated, key=lambda ts: (ts.stage, ts.offered_load())
                    )
                    share = None
                else:
                    total_load = sum(ts.offered_load() for ts in contenders)
                    aggressor = max(
                        contenders, key=lambda ts: ts.offered_load()
                    )
                    share = (
                        aggressor.offered_load() / total_load
                        if total_load > 0
                        else 0.0
                    )
                if (
                    (share is None or share >= self.aggressor_share)
                    and aggressor.stage < 3
                    and (now - aggressor.stage_since) >= self.stage_hold
                ):
                    old = aggressor.stage
                    aggressor.stage = old + 1
                    aggressor.stage_since = now
                    reason = (
                        "slo pressure persists; escalating designated"
                        " aggressor"
                        if share is None
                        else f"slo pressure; aggressor share {share:.2f}"
                        f" of offered load"
                    )
                    transitions.append(
                        (aggressor.name, old, aggressor.stage, reason)
                    )
                    if not self._episode_active:
                        self._episode_active = True
                        self.episodes += 1
                        episode_started = True
                        incident = {
                            "type": "qos-pressure",
                            "tenant": aggressor.name,
                            "stage": aggressor.stage,
                            "share": round(share, 3)
                            if share is not None
                            else None,
                            "reason": reason,
                        }
            else:
                # Stand down one rung per relax_hold when the contest is
                # over — pressure cleared, OR pressure persists but
                # fewer than two tenants are contending (no victim left
                # to defend; residual pressure is not this ladder's to
                # fix).
                reason = (
                    "pressure cleared"
                    if not pressure
                    else "no contending neighbor; standing down"
                )
                for ts in self._tenants.values():
                    if (
                        ts.stage > 0
                        and (now - ts.stage_since) >= self.relax_hold
                    ):
                        old = ts.stage
                        ts.stage = old - 1
                        ts.stage_since = now
                        transitions.append((ts.name, old, ts.stage, reason))
                if self._episode_active and not any(
                    ts.stage > 0 for ts in self._tenants.values()
                ):
                    self._episode_active = False
                    episode_ended = True
            for t in transitions:
                self._transitions.append(
                    {
                        "tenant": t[0],
                        "from": _STAGE_NAMES[t[1]],
                        "to": _STAGE_NAMES[t[2]],
                        "reason": t[3],
                    }
                )
        # journal / incident / metrics OUTSIDE the condition lock: the
        # sinks take their own locks (events journal, flight recorder)
        self._emit(transitions, episode_started, episode_ended, incident)
        return transitions

    def _emit(self, transitions, episode_started, episode_ended, incident):
        if self.stats is not None:
            for tenant, _old, new, _reason in transitions:
                self.stats.count_with_tags(
                    "qos_transition",
                    1,
                    1.0,
                    (f"tenant:{tenant}", f"stage:{_STAGE_NAMES[new]}"),
                )
        journal = None
        if self._journal_fn is not None:
            try:
                journal = self._journal_fn()
            except Exception:  # graftlint: disable=exception-hygiene -- observability tap, never load-bearing
                journal = None
        if journal is not None:
            from pilosa_tpu.obs import events as events_mod

            for tenant, old, new, reason in transitions:
                journal.record(
                    events_mod.EVENT_QOS,
                    tenant=tenant,
                    fromStage=_STAGE_NAMES[old],
                    toStage=_STAGE_NAMES[new],
                    reason=reason,
                )
            if episode_ended:
                journal.record(
                    events_mod.EVENT_QOS,
                    tenant="*",
                    fromStage="episode",
                    toStage="clear",
                    reason="all tenants back to normal",
                )
        if episode_started and incident is not None and self._incident_fn:
            try:
                self._incident_fn(incident)
            except Exception:  # graftlint: disable=exception-hygiene -- incident capture is best-effort; shedding continues without it
                pass

    # -- introspection --------------------------------------------------------

    def snapshot(self) -> dict:
        """/debug/qos payload."""
        with self._cond:
            tenants = {
                ts.name: {
                    "weight": ts.weight,
                    "effectiveWeight": round(
                        ts.effective_weight(self.down_factor), 6
                    ),
                    "stage": ts.stage,
                    "stageName": _STAGE_NAMES[ts.stage],
                    "queued": len(ts.queue),
                    "admitted": ts.admitted,
                    "served": ts.served,
                    "shed": ts.shed,
                    "degraded": ts.degraded,
                    "debtMs": round(ts.debt_ms, 3),
                    "costEstMs": round(ts.cost_est, 4),
                }
                for ts in self._tenants.values()
            }
            return {
                "enabled": self.enabled,
                "vtime": round(self._vtime, 6),
                "episodes": self.episodes,
                "episodeActive": self._episode_active,
                "config": {
                    "downFactor": self.down_factor,
                    "stageHold": self.stage_hold,
                    "relaxHold": self.relax_hold,
                    "tickInterval": self.tick_interval,
                    "retryAfter": self.retry_after,
                    "aggressorShare": self.aggressor_share,
                },
                "tenants": tenants,
                "transitions": list(self._transitions),
            }
