"""Continuous-batching serving plane: coalesce concurrent queries into
micro-batched device dispatches.

BENCH_r05: the batched engine serves 36.5k Count(Intersect) qps/chip,
but one-at-a-time queries through the HTTP path manage 225 — each
request pays its own ~4 ms host fan-out plus the host↔device relay RTT.
This is the gap continuous batching closed for inference servers
(Orca's iteration-level scheduling, vLLM's admission queue): the engine
is fast, the front-end feeds it one request at a time.

Shape: handler threads (ThreadingHTTPServer is thread-per-connection)
:meth:`QueryBatcher.submit` their parsed read-only query and park on an
event; a single dispatcher thread collects an adaptive window of queued
requests and runs them as ONE ``Executor.execute_batch`` pass — the
``_batch_pair_counts``/``_batch_general`` fast paths now amortize the
device launch across *requests*, not just within one request's call
list — then demultiplexes per-request results (or per-request errors)
back to the parked handlers.

Window policy — the window closes on whichever fires first:

* ``size``   — the batch reached ``max_batch``;
* ``age``    — ``window`` seconds elapsed since collection began;
* ``empty``  — the queue is empty and nobody is mid-submit: a lone
  client must never pay window dead time (single-client latency is a
  hard floor — BENCH_r05's 225 qps must not regress);
* ``deadline`` — a collected request is too close to its budget to
  wait out the rest of the window;
* ``drain``  — shutdown: :meth:`close` stops admission and the
  dispatcher finishes everything already queued before exiting.

Deadline accounting (pilosa_tpu/deadline.py): a request whose budget is
already spent 504s at admission without queuing; one that cannot
survive the window bypasses the queue and dispatches immediately on its
own thread; one that expires while queued is completed with
DeadlineExceeded without paying any device work.  The dispatch itself
runs under the most generous remaining budget in the flight (each
request re-checks its OWN budget on wake-up, so a tight budget never
truncates a neighbor's work, and an expired one still 504s).

Observability: ``pilosa_batcher_*`` metrics (depth gauge, window closes
by reason, batch-size distribution, queue-wait histogram, deadline
bypasses/expiries) and per-request ``?profile=true`` attribution — a
``batcher.queueWait`` span tagged with batch size and close reason, a
``batcher.dispatch`` span, and the flight's shared execution profile
grafted as a sub-profile (kernel records for the batched launch).

Write-bearing queries never enter the plane (strict in-order semantics
stay on the per-request path).  On a clustered node the plane fronts the
DISTRIBUTED executor: queries whose shard owners all resolve onto the
local serving mesh (cluster/dist.py mesh_complete) are admitted and a
flight of them dispatches as ONE jit-sharded launch via
``DistributedExecutor.execute_batch``; fan-outs with off-mesh owners
keep the direct path — that leg has its own per-hop batching story
(ROADMAP item 4).

Admission is COST-GOVERNED, not FIFO (server/qos.py): each tenant has
a virtual-time weighted-fair queue whose debt is debited by the
devledger's measured per-tenant device-ms, and the governor's pressure
ladder can deprioritize, degrade (TopN/GroupBy from last-known
semantic-cache entries, marked in the response) or shed (429 +
Retry-After via :class:`~pilosa_tpu.server.qos.ShedError`) an
aggressor tenant when SLO burn alerts fire.  The governor object IS
the queue — it presents ``put``/``get``/``empty`` to the dispatcher
loop below, so window policy and drain semantics are unchanged.
"""

from __future__ import annotations

import logging
import queue
import threading
import time

from pilosa_tpu import deadline
from pilosa_tpu.deadline import DeadlineExceeded
from pilosa_tpu.obs import devledger, qprofile
from pilosa_tpu.server import qos as qos_mod

logger = logging.getLogger(__name__)

_STOP = object()


class _Flight:
    """One queued request: the demux slot its handler thread parks on."""

    __slots__ = (
        "index", "query", "shards", "event", "result", "error", "enqueued",
        "deadline_at", "profiling", "principal", "batch_size", "reason",
        "queue_wait", "dispatch_ms", "batch_profile",
    )

    def __init__(self, index: str, query, shards):
        self.index = index
        self.query = query
        self.shards = shards
        self.event = threading.Event()
        self.result: list | None = None
        self.error: BaseException | None = None
        self.enqueued = time.monotonic()
        # Snapshots of the request's ambient context: the dispatcher
        # thread has neither the deadline nor the profile contextvar.
        self.deadline_at = deadline.at()
        self.profiling = qprofile.profiling()
        # (tenant, index, op_class) for the device cost ledger: the
        # dispatcher attributes the shared batched launch fractionally
        # across every principal whose queries rode the flight.
        self.principal = devledger.current_principal()
        self.batch_size = 0
        self.reason = ""
        self.queue_wait = 0.0
        self.dispatch_ms = 0.0
        self.batch_profile: dict | None = None


class QueryBatcher:
    """Admission queue + dispatcher thread in front of an Executor."""

    def __init__(
        self,
        executor,
        stats=None,
        window: float = 0.002,
        max_batch: int = 64,
        prefetcher=None,
        qos=None,
    ):
        self.executor = executor
        # Flight-driven predictive prefetch (server/prefetch.py): the
        # admission queue knows a flight's full (index, query, shards)
        # set before any kernel launches, so not-yet-resident fragments
        # are staged on the ingest uploader — submit-time staging
        # overlaps the PREVIOUS flight's compute; the window-close pass
        # catches members whose submit-time staging was dropped.
        self.prefetcher = prefetcher
        # gauge/histogram exist on MemStatsClient but not on every
        # StatsClient implementation; degrade to no metrics, not errors
        self.stats = stats if hasattr(stats, "gauge") else None
        self.window = float(window)
        self.max_batch = int(max_batch)
        # The QoS governor doubles as the admission queue: per-tenant
        # virtual-time weighted-fair queues behind the queue.Queue
        # surface the dispatcher loop expects.  A standalone batcher
        # (no server wiring) gets a ladder-disabled governor — WFQ
        # scheduling is always on, pressure control needs SLO/ledger
        # taps.  graftlint: disable=queue-discipline -- depth is bounded by the HTTP handler threads: each blocks on its own flight's result before submitting again
        self.qos = qos if qos is not None else qos_mod.QosGovernor(
            stats=stats, enabled=False
        )
        self._q = self.qos
        self._lock = threading.Lock()
        self._closed = False
        self._depth = 0  # submitted, not yet demuxed (includes in-flight)
        self._depth_peak = 0  # high-water mark since last take_depth_peak
        self.dispatched = 0  # flights dispatched (observability)
        self.coalesced = 0  # requests that shared a flight with >=1 other
        self.rescache_demux = 0  # members served from the semantic cache
        self._thread = threading.Thread(  # graftlint: disable=thread-boundary -- dispatcher is context-free by design: each _Flight snapshots deadline_at/profiling/principal at submit and _dispatch rebuilds the scopes per flight
            target=self._run, name="query-batcher", daemon=True
        )
        self._thread.start()

    # -- admission (handler threads) ----------------------------------------

    def accepts(self, query) -> bool:
        """Read-only parsed queries ride the batch; writes keep strict
        in-order per-request semantics on the direct path."""
        return not self._closed and not query.write_calls()

    def _count_expired(self, tenant: str, reason: str) -> None:
        """Per-tenant, per-reason expiry counter (``batcher_expired``
        keeps its original meaning: expired while queued).  Incident
        bundles can then tell shed (qos_shed) from expired apart."""
        if self.stats is not None:
            self.stats.count_with_tags(
                "batcher_expired_by",
                1,
                1.0,
                (f"tenant:{tenant}", f"reason:{reason}"),
            )

    @staticmethod
    def _degradable(query) -> bool:
        """Only TopN/GroupBy ride the degraded tier: those are the
        shapes PR 14 maintains views for, so a last-known answer is a
        meaningful dashboard, not a stale scalar."""
        calls = getattr(query, "calls", None)
        return bool(calls) and all(
            getattr(c, "name", "") in ("TopN", "GroupBy") for c in calls
        )

    def submit(self, index: str, query, shards=None) -> list:
        """Block the calling handler thread until its flight lands;
        returns the query's results or raises its error.  Runs in the
        request's own deadline scope and profile context."""
        tenant = devledger.current_tenant()
        try:
            deadline.check("batcher admission")
        except DeadlineExceeded:
            self._count_expired(tenant, "admission")
            raise
        # Admission control FIRST: a stage-3 tenant is shed (429 +
        # Retry-After upstream) before it can reach the deadline-bypass
        # or cache-probe fast paths — backpressure must not be dodged
        # by tightening the request budget.
        decision = self.qos.admit(
            tenant, can_degrade=self._degradable(query)
        )
        if decision == qos_mod.DEGRADE:
            stale = getattr(self.executor, "rescache_degraded", None)
            served = stale(index, query, shards) if stale is not None else None
            if served is not None:
                # explicitly-marked degraded tier: API.query() stamps
                # the response envelope from this request-scoped note
                qos_mod.note_degraded()
                self.qos.note_degraded_served(tenant)
                return served
            # no last-known answer: fall through and run it for real
            # (at the tenant's stage-reduced weight)
        if deadline.would_expire_within(self.window):
            # Too close to the budget to queue: dispatch-now beats
            # queue-then-504 (the request still pays only its own work).
            if self.stats is not None:
                self.stats.count("batcher_deadline_bypass", 1, 1.0)
            return self.executor.execute(index, query, shards=shards)
        # Semantic cache probe (exec/rescache.py): a member whose every
        # call hits demuxes instantly — no flight, no queue wait, no
        # device launch.  The probe runs on the handler thread with the
        # profile context live, so ?profile=true carries the
        # rescache.lookup span.
        probe = getattr(self.executor, "rescache_probe", None)
        if probe is not None:
            cached = probe(index, query, shards)
            if cached is not None:
                self.rescache_demux += 1
                if self.stats is not None:
                    self.stats.count("batcher_rescache_demux", 1, 1.0)
                return cached
        if self.prefetcher is not None:
            try:
                # stage this query's cold fragments NOW (handler thread,
                # profile context live -> residency.prefetch span): the
                # upload rides the uploader while the current flight
                # computes, instead of stalling this one's dispatch
                self.prefetcher.prefetch_query(index, query, shards)
            except Exception:
                logger.debug("prefetch failed", exc_info=True)
        item = _Flight(index, query, shards)
        with self._lock:
            direct = self._closed
            if not direct:
                self._depth += 1
                if self._depth > self._depth_peak:
                    self._depth_peak = self._depth
                if self.stats is not None:
                    self.stats.gauge("batcher_depth", self._depth)
                # put under the lock (never blocks: unbounded queue) so
                # close()'s _STOP is strictly FIFO-after every admission
                self._q.put(item)
        if direct:
            return self.executor.execute(index, query, shards=shards)
        rem = deadline.remaining()
        if not item.event.wait(rem if rem is not None else None):
            # our own budget died while queued/dispatching; the
            # dispatcher will still demux into the abandoned slot
            self._count_expired(tenant, "dispatch-wait")
            raise DeadlineExceeded("deadline exceeded (batched dispatch)")
        qprofile.annotate(
            "batcher.queueWait",
            duration_ms=item.queue_wait * 1e3,
            batchSize=item.batch_size,
            closeReason=item.reason,
        )
        qprofile.annotate("batcher.dispatch", duration_ms=item.dispatch_ms)
        if item.batch_profile is not None:
            qprofile.add_subprofile("batcher", item.batch_profile)
        deadline.check("batched response")
        if item.error is not None:
            raise item.error
        return item.result

    # -- dispatcher thread ---------------------------------------------------

    def _run(self) -> None:
        stopping = False
        while not stopping:
            first = self._q.get()
            if first is _STOP:
                break
            batch, reason = self._collect(first)
            stopping = reason == "drain"
            if self.prefetcher is not None:
                try:
                    # window close: the flight's full shard set is known;
                    # re-stage anything whose submit-time prefetch was
                    # dropped while the uploader serviced ingest
                    self.prefetcher.prefetch_flight(
                        [(f.index, f.query, f.shards) for f in batch]
                    )
                except Exception:
                    logger.debug("flight prefetch failed", exc_info=True)
            self._dispatch(batch, reason)
            # governor control loop rides the dispatcher cadence (it
            # has no thread of its own); admission paths tick it too,
            # so a quiet dispatcher still relaxes the ladder
            self.qos.maybe_tick()

    def _urgent(self, item: _Flight) -> bool:
        return (
            item.deadline_at is not None
            and item.deadline_at - time.monotonic() <= self.window
        )

    def _collect(self, first: _Flight) -> tuple[list[_Flight], str]:
        """Adaptive window: grow the batch until size, age, queue-empty
        or a deadline-urgent member closes it (whichever first)."""
        batch = [first]
        urgent = self._urgent(first)
        t_close = time.monotonic() + self.window
        while True:
            if len(batch) >= self.max_batch:
                return batch, "size"
            if urgent:
                return batch, "deadline"
            rem = t_close - time.monotonic()
            if rem <= 0:
                return batch, "age"
            with self._lock:
                idle = self._q.empty() and self._depth <= len(batch)
            if idle:
                # nobody queued or mid-submit: the window must not add
                # dead time (the lone-client latency guarantee)
                return batch, "empty"
            try:
                nxt = self._q.get(timeout=rem)
            except queue.Empty:
                return batch, "age"
            if nxt is _STOP:
                return batch, "drain"
            batch.append(nxt)
            urgent = urgent or self._urgent(nxt)

    def _dispatch(self, batch: list[_Flight], reason: str) -> None:
        now = time.monotonic()
        n = len(batch)
        self.dispatched += 1
        if n > 1:
            self.coalesced += n
        stats = self.stats
        if stats is not None:
            stats.count_with_tags(
                "batcher_window_close", 1, 1.0, (f"reason:{reason}",)
            )
            stats.histogram("batcher_batch_size", n)
        ready: list[_Flight] = []
        for item in batch:
            item.reason = reason
            item.batch_size = n
            item.queue_wait = now - item.enqueued
            if stats is not None:
                stats.timing("batcher_queue_wait", item.queue_wait)
            if item.deadline_at is not None and item.deadline_at <= now:
                # expired while queued: 504 without paying device work
                item.error = DeadlineExceeded(
                    "deadline exceeded (expired in batch queue)"
                )
                if stats is not None:
                    stats.count("batcher_expired", 1, 1.0)
                self._count_expired(item.principal[0], "batch-queue")
            else:
                ready.append(item)
        t0 = time.monotonic()
        try:
            if ready:
                budgets = [
                    f.deadline_at for f in ready if f.deadline_at is not None
                ]
                # Dispatch under the most GENEROUS budget in the flight
                # (each member re-checks its own on wake-up); one
                # budget-less member means an uncapped dispatch.
                budget = (
                    max(budgets) - t0 if len(budgets) == len(ready) else None
                )
                with deadline.scope(budget):
                    self._execute(ready)
        except BaseException as e:
            # a dispatch bug must never strand parked handler threads
            logger.exception("batch dispatch failed")
            for item in ready:
                if item.error is None and item.result is None:
                    item.error = e
        finally:
            dispatch_ms = (time.monotonic() - t0) * 1e3
            for item in batch:
                item.dispatch_ms = dispatch_ms
                item.event.set()
            with self._lock:
                self._depth -= n
                if stats is not None:
                    stats.gauge("batcher_depth", self._depth)

    def _execute(self, ready: list[_Flight]) -> None:
        # one flight may interleave indexes; each index group is one
        # execute_batch pass
        by_index: dict[str, list[_Flight]] = {}
        for item in ready:
            by_index.setdefault(item.index, []).append(item)
        for index, items in by_index.items():
            prof = None
            if any(item.profiling for item in items):
                # shared execution profile for the flight: kernel
                # records of the batched launch, grafted under every
                # profiled member as a sub-profile
                prof = qprofile.QueryProfile(
                    index, f"<batch of {len(items)}>"
                )
            # Weighted ledger attribution: one batched launch, split
            # across the distinct principals riding this flight in
            # proportion to their query count.
            counts: dict[tuple, int] = {}
            for item in items:
                counts[item.principal] = counts.get(item.principal, 0) + 1
            weights = [
                (p, n / len(items)) for p, n in counts.items()
            ]
            t0 = time.perf_counter()
            # window-close planning runs inside execute_batch (after the
            # cache probe, before the batched passes); snapshotting the
            # planner's monotonic counters around the dispatch turns
            # them into per-flight deltas on the shared profile
            # the batcher may wrap the DistributedExecutor facade; the
            # planner lives on the local Executor either way
            pl = getattr(self.executor, "planner", None) or getattr(
                getattr(self.executor, "local", None), "planner", None
            )
            before = (
                (pl.cse_hits, pl.cse_shared, pl.reorders, pl.lane_overrides)
                if pl is not None
                else None
            )
            with qprofile.activate(prof), devledger.weighted_scope(weights):
                outs = self.executor.execute_batch(
                    index, [(item.query, item.shards) for item in items]
                )
                if prof is not None and before is not None:
                    qprofile.annotate(
                        "planner.flight",
                        0.0,
                        cseHits=pl.cse_hits - before[0],
                        cseShared=pl.cse_shared - before[1],
                        reorders=pl.reorders - before[2],
                        laneOverrides=pl.lane_overrides - before[3],
                    )
            prof_dict = None
            if prof is not None:
                prof.finish(time.perf_counter() - t0)
                prof_dict = prof.to_dict()
            for item, out in zip(items, outs):
                if isinstance(out, BaseException):
                    item.error = out
                else:
                    item.result = out
                if item.profiling:
                    item.batch_profile = prof_dict

    # -- lifecycle / introspection ------------------------------------------

    def take_depth_peak(self) -> int:
        """Depth high-water mark since the last call, then reset — the
        flight recorder's per-segment congestion signal (the live gauge
        misses bursts shorter than a scrape interval)."""
        with self._lock:
            peak = self._depth_peak
            self._depth_peak = self._depth
            return peak

    def snapshot(self) -> dict:
        """Serving-plane block for /debug/vars."""
        with self._lock:
            depth = self._depth
        return {
            "depth": depth,
            "window": self.window,
            "maxBatch": self.max_batch,
            "batches": self.dispatched,
            "coalesced": self.coalesced,
            "rescacheDemux": self.rescache_demux,
        }

    def close(self) -> None:
        """Stop admission and drain: every already-queued request is
        dispatched (or deadline-504'd) before the dispatcher exits."""
        with self._lock:
            if self._closed:
                already = True
            else:
                already = False
                self._closed = True
                self._q.put(_STOP)
        if not already or self._thread.is_alive():
            self._thread.join(timeout=30)
