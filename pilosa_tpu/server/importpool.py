"""Bounded import worker pool (reference api.go:66-96, importWorker
:313-348).

The reference queues every import job onto a channel drained by
``importWorkerPoolSize`` goroutines and the HTTP handler blocks on the
job's error channel — a concurrency limiter with backpressure, not
fire-and-forget.  Same shape here, grown two capabilities for the
staged ingest pipeline:

* **Async handles.** ``submit`` blocks only for queue space (the
  backpressure edge) and returns a handle; ``run`` is submit + wait.
  The pipeline submits every shard's drain before waiting on any, so
  independent fragments merge on different workers concurrently.

* **Same-fragment coalescing.** ``submit_merged`` group-commits: while
  a keyed group is queued but not yet started, later submissions for
  the same key piggyback their payload onto it instead of queueing
  another job — N queued imports into one fragment become ONE merged
  apply (one lock acquisition, one op-log batch, one device sync)
  rather than N serialized merges.  Every member gets the group's
  result.

A job submitted FROM a worker thread runs inline instead, so nested
imports (the coordinator's local slice re-entering the API) can never
deadlock the pool.  One "import-drain" job record spans each busy
period (first submission after idle -> last completion) at
``/debug/jobs``; a failing worker terminates it as ``error`` with the
exception text instead of stranding it active.
"""

from __future__ import annotations

import queue
import threading
import time


class Handle:
    """Completion future of one submitted job."""

    __slots__ = ("_event", "_result", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._error = None

    def _finish(self, result=None, error=None) -> None:
        self._result = result
        self._error = error
        self._event.set()

    def wait(self):
        self._event.wait()
        if self._error is not None:
            raise self._error
        return self._result


class _Group:
    """One coalesced same-key batch: payloads accumulate until a worker
    starts the group, then everyone shares the result."""

    __slots__ = ("payloads", "handle", "started")

    def __init__(self, payload):
        self.payloads = [payload]
        self.handle = Handle()
        self.started = False


class ImportPool:
    def __init__(self, workers: int = 2, depth: int = 16, jobs=None, stats=None):
        # depth <= 0 would make the queue unbounded, silently removing
        # the backpressure this pool exists to provide
        self.depth = max(1, depth)
        self.workers = max(1, workers)
        self._q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._local = threading.local()
        self._closed = False
        self.stats = stats
        # submit-side counters (read by /debug/vars and the bench)
        self.blocked_submits = 0
        self.blocked_seconds = 0.0
        self.jobs_run = 0
        self.jobs_coalesced = 0
        self.errors = 0
        # Coalescing state: key -> open (not yet started) group.
        self._groups_lock = threading.Lock()
        self._groups: dict = {}
        # Drain tracking: one "import-drain" job spans each busy period
        # (first submission after idle -> last completion), so a bulk
        # ingest shows up as a single progressing job at /debug/jobs.
        self._jobs = jobs  # JobTracker, optional
        self._drain_lock = threading.Lock()
        self._inflight = 0
        self._drain_job = None
        self._drain_errors = 0
        self._drain_last_error: str | None = None
        if self.stats is not None:
            self.stats.gauge("ingest_pool_depth", self.depth)
            self.stats.gauge("ingest_pool_workers", self.workers)
        self._threads = [
            threading.Thread(target=self._worker, daemon=True, name=f"import-{i}")
            for i in range(self.workers)
        ]
        for t in self._threads:
            t.start()

    # -- drain-job bookkeeping ----------------------------------------------

    def _drain_begin(self) -> None:
        with self._drain_lock:
            self._inflight += 1
            if self._jobs is not None and self._drain_job is None:
                self._drain_job = self._jobs.start("import-drain")
                self._drain_job.set_phase("draining")
                self._drain_errors = 0
                self._drain_last_error = None
        if self.stats is not None:
            self.stats.gauge("ingest_inflight", self._inflight)

    def _drain_end(self, failed: bool, error: str | None = None,
                   advance: bool = True) -> None:
        if failed:
            self.errors += 1
            if self.stats is not None:
                self.stats.count("ingest_errors", 1)
        with self._drain_lock:
            self._inflight -= 1
            inflight = self._inflight
            job = self._drain_job
            if job is not None:
                if failed:
                    self._drain_errors += 1
                    if error:
                        self._drain_last_error = error
                if advance:
                    job.advance(
                        imports_done=1, errors=1 if failed else 0
                    )
                if inflight == 0:
                    # A busy period with failures terminates the record
                    # as error (with the last exception text) instead of
                    # reporting a clean drain.
                    if self._drain_errors:
                        job.finish("error", error=self._drain_last_error)
                    else:
                        job.finish("done")
                    self._drain_job = None
        if self.stats is not None:
            self.stats.gauge("ingest_inflight", inflight)

    def drain_scope(self):
        """Context manager holding the drain record open across a whole
        multi-stage import, so decode/upload stages between pool jobs
        don't close the busy period early."""
        pool = self

        class _Scope:
            def __enter__(self):
                pool._drain_begin()
                return self

            def __exit__(self, et, ev, tb):
                pool._drain_end(
                    failed=ev is not None,
                    error=f"{type(ev).__name__}: {ev}" if ev is not None else None,
                    advance=False,
                )
                return False

        return _Scope()

    def note_phase(self, phase: str) -> None:
        """Per-stage progress on the open drain record (pipeline stages
        report decode/apply/upload through here)."""
        with self._drain_lock:
            if self._drain_job is not None:
                self._drain_job.set_phase(phase)

    def advance(self, **counters) -> None:
        with self._drain_lock:
            if self._drain_job is not None:
                self._drain_job.advance(**counters)

    # -- execution ------------------------------------------------------------

    def _worker(self) -> None:
        self._local.is_worker = True
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, handle = item
            self._run_job(fn, handle)
            self._q.task_done()

    def _run_job(self, fn, handle: Handle) -> None:
        """Execute one job and settle its handle; drain accounting ends
        here — in the executing thread — so a raising worker still
        decrements ``_inflight`` and records the error text."""
        failed, err = False, None
        try:
            handle._finish(result=fn())
        except BaseException as e:  # propagate to the submitter
            failed, err = True, f"{type(e).__name__}: {e}"
            handle._finish(error=e)
        finally:
            self.jobs_run += 1
            self._drain_end(failed, err)

    def _put(self, item) -> None:
        """Bounded enqueue, timing the blocked-submit edge."""
        try:
            self._q.put_nowait(item)
            return
        except queue.Full:
            pass
        self.blocked_submits += 1
        t0 = time.perf_counter()
        self._q.put(item)
        dt = time.perf_counter() - t0
        self.blocked_seconds += dt
        if self.stats is not None:
            self.stats.count("ingest_submit_blocked", 1)
            self.stats.timing("ingest_blocked_submit", dt)

    def submit(self, fn, handle: Handle | None = None) -> Handle:
        """Queue ``fn`` for a pool worker; blocks only while the queue
        is full (backpressure to the ingest client).  Jobs submitted
        from a worker thread (nested imports) run inline — completed by
        return — so the pool can never deadlock on itself."""
        self._drain_begin()
        if handle is None:
            handle = Handle()
        if self._closed or getattr(self._local, "is_worker", False):
            self._run_job(fn, handle)
            return handle
        try:
            self._put((fn, handle))
        except BaseException:
            self._drain_end(failed=True, error="submit failed")
            raise
        return handle

    def run(self, fn):
        """Execute ``fn`` on a pool worker and return its result; blocks
        for queue space (backpressure) and for completion, like the
        reference handler blocking on the job's error channel
        (api.go:330-346)."""
        return self.submit(fn).wait()

    def submit_merged(self, key, payload, fn_many) -> Handle:
        """Coalescing submit: group-commit ``payload`` with any other
        queued-but-unstarted submissions of the same ``key``.  The group
        runs as ONE pool job calling ``fn_many(payloads)`` (in arrival
        order); every member's handle settles with that one result.

        Joining an open group costs no queue slot — that's the point:
        under backlog, N queued same-fragment jobs collapse into one
        merged apply instead of N serialized merges."""
        with self._groups_lock:
            group = self._groups.get(key)
            if group is not None and not group.started:
                group.payloads.append(payload)
                self.jobs_coalesced += 1
                if self.stats is not None:
                    self.stats.count("ingest_jobs_coalesced", 1)
                return group.handle
            group = _Group(payload)
            self._groups[key] = group

        def run_group():
            with self._groups_lock:
                group.started = True
                if self._groups.get(key) is group:
                    del self._groups[key]
                payloads = list(group.payloads)
            return fn_many(payloads)

        # The group's shared handle rides the pool job directly: when the
        # worker settles it, every member — first submitter and joiners
        # alike — wakes with the same result.
        return self.submit(run_group, handle=group.handle)

    def wait_all(self, handles) -> None:
        """Wait every handle; raises the first error AFTER all have
        settled (a failing shard must not leave later drains un-awaited)."""
        first: BaseException | None = None
        for h in handles:
            try:
                h.wait()
            except BaseException as e:
                if first is None:
                    first = e
        if first is not None:
            raise first

    # -- introspection --------------------------------------------------------

    def snapshot(self) -> dict:
        with self._drain_lock:
            inflight = self._inflight
        return {
            "workers": self.workers,
            "depth": self.depth,
            "queueLen": self._q.qsize(),
            "inflight": inflight,
            "jobsRun": self.jobs_run,
            "jobsCoalesced": self.jobs_coalesced,
            "errors": self.errors,
            "blockedSubmits": self.blocked_submits,
            "blockedSeconds": round(self.blocked_seconds, 6),
        }

    def close(self) -> None:
        self._closed = True
        for _ in self._threads:
            self._q.put(None)
        for t in self._threads:
            t.join(timeout=5)
