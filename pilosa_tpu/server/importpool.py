"""Bounded import worker pool (reference api.go:66-96, importWorker
:313-348).

The reference queues every import job onto a channel drained by
``importWorkerPoolSize`` goroutines and the HTTP handler blocks on the
job's error channel — a concurrency limiter with backpressure, not
fire-and-forget.  Same shape here: ``run`` submits a job to a bounded
queue and waits for its result; when the queue is full, submission blocks
(backpressure to the ingest client).  A job submitted FROM a worker
thread runs inline instead, so nested imports (the coordinator's local
slice re-entering the API) can never deadlock the pool.
"""

from __future__ import annotations

import queue
import threading


class ImportPool:
    def __init__(self, workers: int = 2, depth: int = 16):
        # depth <= 0 would make the queue unbounded, silently removing
        # the backpressure this pool exists to provide
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._local = threading.local()
        self._closed = False
        self._threads = [
            threading.Thread(target=self._worker, daemon=True, name=f"import-{i}")
            for i in range(max(1, workers))
        ]
        for t in self._threads:
            t.start()

    def _worker(self) -> None:
        self._local.is_worker = True
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, done = item
            try:
                done["result"] = fn()
            except BaseException as e:  # propagate to the submitter
                done["error"] = e
            finally:
                done["event"].set()
                self._q.task_done()

    def run(self, fn):
        """Execute ``fn`` on a pool worker and return its result; blocks
        for queue space (backpressure) and for completion, like the
        reference handler blocking on the job's error channel
        (api.go:330-346)."""
        if self._closed or getattr(self._local, "is_worker", False):
            return fn()
        done = {"event": threading.Event()}
        self._q.put((fn, done))
        done["event"].wait()
        if "error" in done:
            raise done["error"]
        return done["result"]

    def close(self) -> None:
        self._closed = True
        for _ in self._threads:
            self._q.put(None)
        for t in self._threads:
            t.join(timeout=5)
