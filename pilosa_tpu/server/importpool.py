"""Bounded import worker pool (reference api.go:66-96, importWorker
:313-348).

The reference queues every import job onto a channel drained by
``importWorkerPoolSize`` goroutines and the HTTP handler blocks on the
job's error channel — a concurrency limiter with backpressure, not
fire-and-forget.  Same shape here: ``run`` submits a job to a bounded
queue and waits for its result; when the queue is full, submission blocks
(backpressure to the ingest client).  A job submitted FROM a worker
thread runs inline instead, so nested imports (the coordinator's local
slice re-entering the API) can never deadlock the pool.
"""

from __future__ import annotations

import queue
import threading


class ImportPool:
    def __init__(self, workers: int = 2, depth: int = 16, jobs=None):
        # depth <= 0 would make the queue unbounded, silently removing
        # the backpressure this pool exists to provide
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._local = threading.local()
        self._closed = False
        # Drain tracking: one "import-drain" job spans each busy period
        # (first submission after idle -> last completion), so a bulk
        # ingest shows up as a single progressing job at /debug/jobs.
        self._jobs = jobs  # JobTracker, optional
        self._drain_lock = threading.Lock()
        self._inflight = 0
        self._drain_job = None
        self._threads = [
            threading.Thread(target=self._worker, daemon=True, name=f"import-{i}")
            for i in range(max(1, workers))
        ]
        for t in self._threads:
            t.start()

    # -- drain-job bookkeeping ----------------------------------------------

    def _drain_begin(self) -> None:
        if self._jobs is None:
            return
        with self._drain_lock:
            self._inflight += 1
            if self._drain_job is None:
                self._drain_job = self._jobs.start("import-drain")
                self._drain_job.set_phase("draining")

    def _drain_end(self, failed: bool) -> None:
        if self._jobs is None:
            return
        with self._drain_lock:
            self._inflight -= 1
            job = self._drain_job
            if job is None:
                return
            job.advance(imports_done=1, errors=1 if failed else 0)
            if self._inflight == 0:
                job.finish("done")
                self._drain_job = None

    def _worker(self) -> None:
        self._local.is_worker = True
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, done = item
            try:
                done["result"] = fn()
            except BaseException as e:  # propagate to the submitter
                done["error"] = e
            finally:
                done["event"].set()
                self._q.task_done()

    def run(self, fn):
        """Execute ``fn`` on a pool worker and return its result; blocks
        for queue space (backpressure) and for completion, like the
        reference handler blocking on the job's error channel
        (api.go:330-346)."""
        self._drain_begin()
        failed = False
        try:
            if self._closed or getattr(self._local, "is_worker", False):
                try:
                    return fn()
                except BaseException:
                    failed = True
                    raise
            done = {"event": threading.Event()}
            self._q.put((fn, done))
            done["event"].wait()
            if "error" in done:
                failed = True
                raise done["error"]
            return done["result"]
        finally:
            self._drain_end(failed)

    def close(self) -> None:
        self._closed = True
        for _ in self._threads:
            self._q.put(None)
        for t in self._threads:
            t.join(timeout=5)
