"""Request deadlines: one budget, propagated end to end.

Every query/import may carry a deadline — derived from a per-request
``timeout=`` HTTP param, an ``X-Pilosa-Deadline`` header from an
upstream node, or the server's configured default.  The deadline lives
in a ``contextvars.ContextVar`` so it follows the request through the
handler thread AND into the distributed executor's fan-out pool
(``dist._submit`` copies the caller's context), and every remote hop
re-derives its per-hop socket timeout from the remaining budget
(``cluster/client.py``).

Wire format: the header carries the REMAINING budget in seconds at send
time (not an absolute timestamp), so clock skew between nodes never
inflates or deflates a deadline; each hop only loses the network
transit time, which is exactly the cost the budget should pay.

An expired deadline raises :class:`DeadlineExceeded`, mapped to HTTP
504 by ``server/http.py`` — a slow fan-out fails fast instead of
stalling the pool (the reference bounds this with contexts threaded
through executor.go; contextvars is this runtime's equivalent).
"""

from __future__ import annotations

import contextvars
import time
from contextlib import contextmanager

# Header carrying the remaining budget (seconds, decimal) across hops.
HEADER = "X-Pilosa-Deadline"


class DeadlineExceeded(Exception):
    """The request's deadline budget is exhausted (served as HTTP 504).

    Deliberately NOT an ExecuteError/ApiError subclass: those map to
    HTTP 400 and a deadline expiry is not a client mistake.
    """


_deadline: contextvars.ContextVar[float | None] = contextvars.ContextVar(
    "pilosa_deadline", default=None
)


def start(budget_seconds: float) -> contextvars.Token:
    """Install an absolute monotonic deadline ``budget_seconds`` from now."""
    return _deadline.set(time.monotonic() + float(budget_seconds))


def reset(token: contextvars.Token) -> None:
    _deadline.reset(token)


@contextmanager
def scope(budget_seconds: float | None):
    """``with deadline.scope(1.5): ...`` — no-op when budget is None/<=0."""
    if budget_seconds is None or budget_seconds <= 0:
        yield
        return
    token = start(budget_seconds)
    try:
        yield
    finally:
        reset(token)


def remaining() -> float | None:
    """Seconds left in the active budget; None when no deadline is set.
    May be negative once expired."""
    d = _deadline.get()
    if d is None:
        return None
    return d - time.monotonic()


def expired() -> bool:
    r = remaining()
    return r is not None and r <= 0


def at() -> float | None:
    """Absolute monotonic deadline of the active budget (None when no
    deadline is set).  For handing a budget across threads: the serving
    plane's dispatcher (``server/batcher.py``) runs outside the request
    context, so the submitting thread snapshots this value into the
    queue item and the dispatcher compares it against
    ``time.monotonic()`` directly."""
    return _deadline.get()


def would_expire_within(seconds: float) -> bool:
    """Queue-time admission accounting: True when the active budget
    cannot survive ``seconds`` more of waiting.  The batcher uses this
    to classify a request as too close to its deadline to queue — it
    must dispatch immediately (or 504) rather than wait out a batch
    window it cannot afford.  False when no deadline is set."""
    r = remaining()
    return r is not None and r <= seconds


def check(what: str = "") -> None:
    """Raise :class:`DeadlineExceeded` if the active budget is exhausted."""
    r = remaining()
    if r is not None and r <= 0:
        raise DeadlineExceeded(
            f"deadline exceeded{f' ({what})' if what else ''}"
        )


def header_value() -> str | None:
    """Remaining budget formatted for the wire; None when no deadline."""
    r = remaining()
    if r is None:
        return None
    return format(max(r, 0.0), ".4f")


def from_header(value: str | None) -> float | None:
    """Parse an incoming header into a budget (seconds); None when absent
    or malformed (a garbage header must not 500 the request — the
    request simply runs without a deadline)."""
    if not value:
        return None
    try:
        budget = float(value)
    except ValueError:
        return None
    if budget != budget or budget == float("inf"):  # NaN / inf
        return None
    return max(budget, 0.0)
