"""PQL — the Pilosa Query Language (reference: pql/ directory).

A pure host-side layer: grammar-compatible parser producing the same
Call/Condition AST shape as the reference (pql/ast.go:27,263,482), consumed
by the executor which lowers ASTs to jitted XLA computations.
"""

from pilosa_tpu.pql.ast import Call, Condition, Query
from pilosa_tpu.pql.parser import ParseError, parse

__all__ = ["Call", "Condition", "Query", "ParseError", "parse"]
