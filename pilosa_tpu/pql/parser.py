"""Recursive-descent PQL parser.

Grammar-compatible with the reference PEG (pql/pql.peg, 83 lines; generated
parser pql/pql.peg.go). Implemented as a fresh hand-rolled recursive
descent with explicit backtracking where the PEG uses ordered choice
(notably ``Range(f=5, from, to)`` vs generic ``Range(f > 5)``, and the
special call forms falling back to the generic ``IDENT(allargs)`` rule).
"""

from __future__ import annotations

import re
import threading
from collections import OrderedDict
from typing import Any

from pilosa_tpu.pql.ast import Call, Condition, Query

_TIMESTAMP_RE = re.compile(r"\d{4}-[01]\d-[0-3]\dT\d\d:\d\d$")
_NUMBER_RE = re.compile(r"-?(\d+(\.\d*)?|\.\d+)$")
_IDENT_RE = re.compile(r"[A-Za-z][A-Za-z0-9]*")
_FIELD_RE = re.compile(r"[A-Za-z][A-Za-z0-9_-]*")
_RESERVED_FIELDS = ("_row", "_col", "_start", "_end", "_timestamp", "_field")
# Bare-word value charset (pql.peg:50) extended with '.' so numbers and the
# classifier below can share one scan.
_BARE_RE = re.compile(r"[A-Za-z0-9\-_:.]+")


class ParseError(Exception):
    def __init__(self, msg: str, pos: int = 0):
        super().__init__(f"{msg} at position {pos}")
        self.pos = pos


class _Parser:
    def __init__(self, src: str):
        self.src = src
        self.pos = 0

    # -- low-level helpers --------------------------------------------------

    def error(self, msg: str):
        raise ParseError(msg, self.pos)

    def sp(self) -> None:
        while self.pos < len(self.src) and self.src[self.pos] in " \t\n\r":
            self.pos += 1

    def eof(self) -> bool:
        return self.pos >= len(self.src)

    def peek(self) -> str:
        return self.src[self.pos] if self.pos < len(self.src) else ""

    def lit(self, s: str) -> bool:
        if self.src.startswith(s, self.pos):
            self.pos += len(s)
            return True
        return False

    def expect(self, s: str) -> None:
        if not self.lit(s):
            self.error(f"expected {s!r}")

    def comma(self) -> bool:
        save = self.pos
        self.sp()
        if self.lit(","):
            self.sp()
            return True
        self.pos = save
        return False

    def regex(self, rx: re.Pattern) -> str | None:
        m = rx.match(self.src, self.pos)
        if not m:
            return None
        self.pos = m.end()
        return m.group(0)

    # -- grammar ------------------------------------------------------------

    def parse(self) -> Query:
        q = Query()
        self.sp()
        while not self.eof():
            q.calls.append(self.call())
            self.sp()
        return q

    def call(self) -> Call:
        save = self.pos
        name = self.regex(_IDENT_RE)
        if not name:
            self.error("expected call name")
        # Special forms match the exact literal name (PEG ordered choice,
        # pql.peg:9-17); on failure fall back to the generic IDENT rule.
        specials = {
            "Set": self._call_set,
            "SetRowAttrs": self._call_setrowattrs,
            "SetColumnAttrs": self._call_setcolumnattrs,
            "Clear": self._call_clear,
            "ClearRow": self._call_clearrow,
            "Store": self._call_store,
            "TopN": self._call_topn,
            "Rows": self._call_rows,
            "Range": self._call_range,
        }
        special = specials.get(name)
        if special is not None:
            try:
                return special()
            except ParseError:
                self.pos = save
                name = self.regex(_IDENT_RE)
        return self._generic_call(name)

    def _open(self) -> None:
        self.expect("(")
        self.sp()

    def _close(self) -> None:
        self.sp()
        self.expect(")")

    def _generic_call(self, name: str) -> Call:
        # IDENT open allargs comma? close (pql.peg:18)
        call = Call(name)
        self._open()
        self._allargs(call)
        self.comma()
        self._close()
        return call

    def _allargs(self, call: Call) -> None:
        # allargs <- Call (comma Call)* (comma args)? / args / sp (pql.peg:19)
        save = self.pos
        try:
            call.children.append(self.call())
            while True:
                save2 = self.pos
                if not self.comma():
                    break
                try:
                    call.children.append(self.call())
                except ParseError:
                    self.pos = save2
                    if self.comma():
                        self._args(call)
                    break
            return
        except ParseError:
            self.pos = save
        save = self.pos
        try:
            self._args(call)
            return
        except ParseError:
            self.pos = save
        self.sp()

    def _args(self, call: Call) -> None:
        self._arg(call)
        while True:
            save = self.pos
            if not self.comma():
                break
            # trailing comma before ')' belongs to the caller
            try:
                self._arg(call)
            except ParseError:
                self.pos = save
                break
        self.sp()

    def _arg(self, call: Call) -> None:
        # ternary conditional starts with an integer (pql.peg:34-37)
        c = self.peek()
        if c.isdigit() or c == "-":
            self._ternary(call)
            return
        fname = self._field_name()
        self.sp()
        for op in ("><", "<=", ">=", "==", "!=", "<", ">", "="):
            if self.lit(op):
                self.sp()
                value = self.value()
                if op == "=":
                    call.args[fname] = value
                else:
                    call.args[fname] = Condition(op, value)
                return
        self.error("expected '=' or comparison operator")

    def _ternary(self, call: Call) -> None:
        lo = self._int()
        self.sp()
        lo_op = "<=" if self.lit("<=") else ("<" if self.lit("<") else self.error("expected < or <="))
        self.sp()
        fname = self._field_name()
        self.sp()
        hi_op = "<=" if self.lit("<=") else ("<" if self.lit("<") else self.error("expected < or <="))
        self.sp()
        hi = self._int()
        call.args[fname] = Condition(f"{lo_op}x{hi_op}", [lo, hi])

    def _int(self) -> int:
        m = re.compile(r"-?\d+").match(self.src, self.pos)
        if not m:
            self.error("expected integer")
        self.pos = m.end()
        return int(m.group(0))

    def _field_name(self) -> str:
        for r in _RESERVED_FIELDS:
            if self.src.startswith(r, self.pos):
                self.pos += len(r)
                return r
        name = self.regex(_FIELD_RE)
        if not name:
            self.error("expected field name")
        return name

    # -- values -------------------------------------------------------------

    def value(self) -> Any:
        self.sp()
        c = self.peek()
        if c == "[":
            self.pos += 1
            self.sp()
            items = []
            if self.peek() != "]":
                while True:
                    items.append(self.value())
                    if not self.comma():
                        break
            self.sp()
            self.expect("]")
            self.sp()
            return items
        if c == '"':
            return self._dquoted()
        if c == "'":
            return self._squoted()
        save = self.pos
        tok = self.regex(_BARE_RE)
        if tok is None:
            self.error("expected value")
        follows_call = self.peek() == "("
        # classify the bare token (pql.peg:43-53 item ordering)
        if not follows_call:
            if tok in ("null", "true", "false") and self._at_delim():
                return {"null": None, "true": True, "false": False}[tok]
            if _TIMESTAMP_RE.fullmatch(tok):
                return tok
            if _NUMBER_RE.fullmatch(tok):
                return float(tok) if "." in tok else int(tok)
            return tok
        if _IDENT_RE.fullmatch(tok):
            self.pos = save
            return self.call()
        self.error(f"unexpected token {tok!r}")

    def _at_delim(self) -> bool:
        save = self.pos
        self.sp()
        ok = self.peek() in (",", ")", "]", "")
        self.pos = save
        return ok

    def _dquoted(self) -> str:
        self.expect('"')
        out = []
        while True:
            c = self.peek()
            if c == "":
                self.error("unterminated string")
            if c == '"':
                self.pos += 1
                return "".join(out)
            if c == "\\" and self.pos + 1 < len(self.src) and self.src[self.pos + 1] in '"\\':
                out.append(self.src[self.pos + 1])
                self.pos += 2
            else:
                out.append(c)
                self.pos += 1

    def _squoted(self) -> str:
        self.expect("'")
        out = []
        while True:
            c = self.peek()
            if c == "":
                self.error("unterminated string")
            if c == "'":
                self.pos += 1
                return "".join(out)
            if c == "\\" and self.pos + 1 < len(self.src) and self.src[self.pos + 1] in "'\\":
                out.append(self.src[self.pos + 1])
                self.pos += 2
            else:
                out.append(c)
                self.pos += 1

    # -- positional helpers -------------------------------------------------

    def _pos_num_or_str(self, call: Call, key: str) -> None:
        # col / row rule (pql.peg:63-70): uint or quoted string
        c = self.peek()
        if c == '"':
            call.args[key] = self._dquoted()
        elif c == "'":
            call.args[key] = self._squoted()
        else:
            tok = self.regex(re.compile(r"\d+"))
            if tok is None:
                self.error(f"expected {key} value")
            call.args[key] = int(tok)

    def _posfield(self, call: Call) -> None:
        name = self.regex(_FIELD_RE)
        if not name:
            self.error("expected field name")
        call.args["_field"] = name

    def _timestampfmt(self) -> str:
        c = self.peek()
        if c in "\"'":
            quote = c
            self.pos += 1
            tok = self.regex(re.compile(r"\d{4}-[01]\d-[0-3]\dT\d\d:\d\d"))
            if tok is None:
                self.error("expected timestamp")
            self.expect(quote)
            return tok
        tok = self.regex(re.compile(r"\d{4}-[01]\d-[0-3]\dT\d\d:\d\d"))
        if tok is None:
            self.error("expected timestamp")
        return tok

    # -- special call forms (pql.peg:9-17) ----------------------------------

    def _call_set(self) -> Call:
        call = Call("Set")
        self._open()
        self._pos_num_or_str(call, "_col")
        if not self.comma():
            self.error("expected ','")
        self._args(call)
        save = self.pos
        if self.comma():
            try:
                call.args["_timestamp"] = self._timestampfmt()
            except ParseError:
                self.pos = save
        self._close()
        return call

    def _call_setrowattrs(self) -> Call:
        call = Call("SetRowAttrs")
        self._open()
        self._posfield(call)
        if not self.comma():
            self.error("expected ','")
        self._pos_num_or_str(call, "_row")
        if not self.comma():
            self.error("expected ','")
        self._args(call)
        self._close()
        return call

    def _call_setcolumnattrs(self) -> Call:
        call = Call("SetColumnAttrs")
        self._open()
        self._pos_num_or_str(call, "_col")
        if not self.comma():
            self.error("expected ','")
        self._args(call)
        self._close()
        return call

    def _call_clear(self) -> Call:
        call = Call("Clear")
        self._open()
        self._pos_num_or_str(call, "_col")
        if not self.comma():
            self.error("expected ','")
        self._args(call)
        self._close()
        return call

    def _call_clearrow(self) -> Call:
        call = Call("ClearRow")
        self._open()
        self._arg(call)
        self._close()
        return call

    def _call_store(self) -> Call:
        call = Call("Store")
        self._open()
        call.children.append(self.call())
        if not self.comma():
            self.error("expected ','")
        self._arg(call)
        self._close()
        return call

    def _call_topn(self) -> Call:
        return self._posfield_call("TopN")

    def _call_rows(self) -> Call:
        return self._posfield_call("Rows")

    def _posfield_call(self, name: str) -> Call:
        call = Call(name)
        self._open()
        self._posfield(call)
        if self.comma():
            self._allargs(call)
        self._close()
        return call

    def _call_range(self) -> Call:
        # 'Range' open field '=' value comma 'from='? ts comma 'to='? ts close
        call = Call("Range")
        self._open()
        fname = self._field_name()
        self.sp()
        self.expect("=")
        self.sp()
        call.args[fname] = self.value()
        if not self.comma():
            self.error("expected ','")
        self.lit("from=")
        call.args["from"] = self._timestampfmt()
        if not self.comma():
            self.error("expected ','")
        self.lit("to=")
        self.sp()
        call.args["to"] = self._timestampfmt()
        self._close()
        return call


# Parsed-AST cache for SHORT queries (the serving shapes — lone counts,
# TopN, GroupBy — repeat with varying literals, and parsing costs ~half
# of a warm cache-served round trip).  Long strings (bulk write batches)
# are one-shot and would only bloat the key memory, so they bypass.
# Cached Querys are never handed out directly: callers receive a fresh
# clone per parse, because the executor mutates call args in place
# (key translation).
_PARSE_CACHE_MAX_LEN = 256
_parse_cache: "OrderedDict[str, Query]" = OrderedDict()
_PARSE_CACHE_ENTRIES = 4096
_parse_cache_lock = threading.Lock()


def parse(src: str) -> Query:
    """Parse a PQL string into a Query (reference pql/parser.go Parse)."""
    if len(src) > _PARSE_CACHE_MAX_LEN:
        return _Parser(src).parse()
    with _parse_cache_lock:
        q = _parse_cache.get(src)
        if q is not None:
            _parse_cache.move_to_end(src)
            return Query([c.clone() for c in q.calls])
    q = _Parser(src).parse()
    with _parse_cache_lock:
        _parse_cache[src] = Query([c.clone() for c in q.calls])
        while len(_parse_cache) > _PARSE_CACHE_ENTRIES:
            _parse_cache.popitem(last=False)
    return q
