"""PQL abstract syntax tree.

Mirrors the reference AST surface (pql/ast.go): ``Query`` holds top-level
``Call``s; a ``Call`` has a name, keyword args (scalars, lists, strings,
``Condition``s, or nested ``Call``s) and child calls; a ``Condition``
carries a comparison operator and bound(s) for BSI range predicates
(pql/ast.go:482).

Positional tokens use the reference's reserved arg keys (pql/pql.peg:60-61):
``_col``, ``_row``, ``_field``, ``_timestamp``, ``_start``, ``_end``.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any

# Ternary condition ops combine the two comparators of `a < field < b`
# (reference pql/pql.peg:34-37, token.go BTWN_* tokens).
TERNARY_OPS = {"<x<", "<=x<", "<x<=", "<=x<="}
BINARY_OPS = {"<", ">", "<=", ">=", "==", "!=", "><"}


@dataclass
class Condition:
    """A comparison predicate attached to a field arg
    (reference pql/ast.go:482 ``Condition``)."""

    op: str
    value: Any  # scalar, or [lo, hi] for '><' and ternary ops

    def __str__(self) -> str:
        if self.op in TERNARY_OPS:
            lo_op, hi_op = self.op.split("x")
            return f"{self.value[0]} {lo_op} x {hi_op} {self.value[1]}"
        return f"{self.op} {_format_value(self.value)}"

    def int_pair(self) -> tuple[int, int]:
        if not (isinstance(self.value, (list, tuple)) and len(self.value) == 2):
            raise ValueError(f"condition {self.op} requires a [lo, hi] pair")
        return int(self.value[0]), int(self.value[1])


@dataclass
class Call:
    """One PQL call (reference pql/ast.go:263)."""

    name: str
    args: dict[str, Any] = dc_field(default_factory=dict)
    children: list["Call"] = dc_field(default_factory=list)

    # -- typed arg accessors (reference pql/ast.go:272-392) ----------------

    def arg(self, key: str) -> tuple[Any, bool]:
        if key in self.args:
            return self.args[key], True
        return None, False

    def uint_arg(self, key: str) -> tuple[int | None, bool]:
        v = self.args.get(key)
        if v is None:
            return None, False
        if isinstance(v, bool) or not isinstance(v, int):
            raise TypeError(f"arg {key!r} must be an unsigned integer, got {v!r}")
        if v < 0:
            raise TypeError(f"arg {key!r} must be non-negative, got {v}")
        return v, True

    def int_arg(self, key: str) -> tuple[int | None, bool]:
        v = self.args.get(key)
        if v is None:
            return None, False
        if isinstance(v, bool) or not isinstance(v, int):
            raise TypeError(f"arg {key!r} must be an integer, got {v!r}")
        return v, True

    def string_arg(self, key: str) -> tuple[str | None, bool]:
        v = self.args.get(key)
        if v is None:
            return None, False
        if not isinstance(v, str):
            raise TypeError(f"arg {key!r} must be a string, got {v!r}")
        return v, True

    def bool_arg(self, key: str) -> tuple[bool | None, bool]:
        v = self.args.get(key)
        if v is None:
            return None, False
        if not isinstance(v, bool):
            raise TypeError(f"arg {key!r} must be a bool, got {v!r}")
        return v, True

    def uint_slice_arg(self, key: str) -> tuple[list[int] | None, bool]:
        v = self.args.get(key)
        if v is None:
            return None, False
        if not isinstance(v, list):
            raise TypeError(f"arg {key!r} must be a list, got {v!r}")
        out = []
        for x in v:
            if isinstance(x, bool) or not isinstance(x, int) or x < 0:
                raise TypeError(f"arg {key!r} must hold unsigned ints, got {x!r}")
            out.append(x)
        return out, True

    def call_arg(self, key: str) -> tuple["Call | None", bool]:
        v = self.args.get(key)
        if v is None:
            return None, False
        if not isinstance(v, Call):
            raise TypeError(f"arg {key!r} must be a call, got {v!r}")
        return v, True

    def condition_arg(self, key: str) -> tuple[Condition | None, bool]:
        v = self.args.get(key)
        if v is None:
            return None, False
        if not isinstance(v, Condition):
            return Condition("==", v), True
        return v, True

    def field_arg(self) -> str | None:
        """The single non-reserved arg key, for calls like Row(f=1)
        (reference pql/ast.go:360-392 FieldArg)."""
        for k in self.args:
            if not k.startswith("_") and k not in ("from", "to"):
                return k
        return None

    def has_conditions(self) -> bool:
        return any(isinstance(v, Condition) for v in self.args.values())

    def clone(self) -> "Call":
        def copy_value(v):
            if isinstance(v, Call):
                return v.clone()
            if isinstance(v, Condition):
                return Condition(
                    v.op, list(v.value) if isinstance(v.value, list) else v.value
                )
            if isinstance(v, list):
                return [copy_value(x) for x in v]
            return v

        return Call(
            self.name,
            {k: copy_value(v) for k, v in self.args.items()},
            [c.clone() for c in self.children],
        )

    def __str__(self) -> str:
        parts = [str(c) for c in self.children]
        for k in sorted(self.args):
            v = self.args[k]
            if isinstance(v, Condition):
                if v.op in TERNARY_OPS:
                    lo_op, hi_op = v.op.split("x")
                    parts.append(f"{v.value[0]} {lo_op} {k} {hi_op} {v.value[1]}")
                else:
                    parts.append(f"{k} {v.op} {_format_value(v.value)}")
            else:
                parts.append(f"{k}={_format_value(v)}")
        return f"{self.name}({', '.join(parts)})"

    __repr__ = __str__


@dataclass
class Query:
    """A parsed PQL query: one or more calls (reference pql/ast.go:27)."""

    calls: list[Call] = dc_field(default_factory=list)

    def write_calls(self) -> list[Call]:
        """Calls that mutate data (reference pql/ast.go WriteCallN)."""
        writes = {"Set", "Clear", "ClearRow", "Store", "SetRowAttrs", "SetColumnAttrs"}
        return [c for c in self.calls if c.name in writes]

    def __str__(self) -> str:
        return "".join(str(c) for c in self.calls)


def _format_value(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return "null"
    if isinstance(v, str):
        return f'"{v}"'
    if isinstance(v, list):
        return "[" + ",".join(_format_value(x) for x in v) + "]"
    if isinstance(v, Call):
        return str(v)
    return str(v)
