"""System information (reference: gopsutil/ SystemInfo — uptime,
platform, memory; server.go:793-835 monitorRuntime feeds it into stats).

The reference shells out to gopsutil; here everything reads /proc
directly (Linux-only, graceful zeros elsewhere) plus JAX device
inventory — the TPU-native addition: accelerator kind/count belong in a
TPU framework's system report.
"""

from __future__ import annotations

import os
import platform
import threading
import time

# fallback process start time where /proc is unavailable
_IMPORT_TIME = time.time()


def build_info_text(version: str) -> str:
    """Prometheus ``build_info`` exposition block (the node_exporter
    idiom: a constant 1-valued gauge whose labels carry the versions)."""
    try:
        import jax

        jax_version = jax.__version__
    except Exception:
        jax_version = ""
    py = platform.python_version()
    return (
        "# HELP pilosa_build_info build/version identity "
        "(constant 1; labels carry the versions)\n"
        "# TYPE pilosa_build_info gauge\n"
        f'pilosa_build_info{{version="{version}",jax="{jax_version}",'
        f'python="{py}"}} 1\n'
    )


class SystemInfo:
    """reference gopsutil/gopsutil.go systemInfo."""

    _boot_time: float | None = None

    def uptime(self) -> int:
        """Seconds since host boot (reference Uptime)."""
        try:
            with open("/proc/uptime") as f:
                return int(float(f.read().split()[0]))
        except OSError:
            return 0

    def platform(self) -> str:
        return platform.system().lower()

    def family(self) -> str:
        return platform.machine()

    def os_version(self) -> str:
        return platform.release()

    def kernel_version(self) -> str:
        return platform.version()

    def _meminfo(self) -> dict[str, int]:
        out: dict[str, int] = {}
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    key, _, rest = line.partition(":")
                    val = rest.split()
                    if val:
                        out[key] = int(val[0]) * 1024  # kB -> bytes
        except OSError:
            pass
        return out

    def mem_total(self) -> int:
        return self._meminfo().get("MemTotal", 0)

    def mem_free(self) -> int:
        m = self._meminfo()
        return m.get("MemAvailable", m.get("MemFree", 0))

    def mem_used(self) -> int:
        m = self._meminfo()
        total = m.get("MemTotal", 0)
        return total - m.get("MemAvailable", m.get("MemFree", 0)) if total else 0

    def cpu_count(self) -> int:
        return os.cpu_count() or 0

    def thread_count(self) -> int:
        """Live Python threads — the goroutine-count analogue."""
        return threading.active_count()

    def process_rss(self) -> int:
        """Resident set size of this process in bytes."""
        try:
            with open("/proc/self/statm") as f:
                pages = int(f.read().split()[1])
            return pages * os.sysconf("SC_PAGE_SIZE")
        except (OSError, ValueError):
            return 0

    def process_start_time(self) -> float:
        """Unix time this PROCESS started (the host ``uptime`` above is
        boot time, not ours).  /proc/self/stat field 22 is start time
        in clock ticks since boot; boot time is /proc/stat ``btime``.
        Falls back to module-import time off Linux."""
        try:
            with open("/proc/self/stat") as f:
                # comm (field 2) may contain spaces; split after the
                # closing paren so field indices stay stable
                rest = f.read().rsplit(")", 1)[1].split()
            ticks = float(rest[19])  # field 22, 0-indexed after comm
            with open("/proc/stat") as f:
                for line in f:
                    if line.startswith("btime "):
                        btime = float(line.split()[1])
                        break
                else:
                    return _IMPORT_TIME
            return btime + ticks / os.sysconf("SC_CLK_TCK")
        except (OSError, ValueError, IndexError):
            return _IMPORT_TIME

    def process_uptime(self) -> float:
        """Seconds since this process started."""
        return max(0.0, time.time() - self.process_start_time())

    def process_block(self, version: str = "") -> dict:
        """The ``process`` block for /debug/vars: this process's own
        identity and age, distinct from the host report above."""
        try:
            import jax

            jax_version = jax.__version__
        except Exception:
            jax_version = ""
        return {
            "pid": os.getpid(),
            "version": version,
            "python": platform.python_version(),
            "jax": jax_version,
            "startTime": self.process_start_time(),
            "uptimeSeconds": round(self.process_uptime(), 3),
            "rssBytes": self.process_rss(),
            "threads": self.thread_count(),
        }

    def devices(self) -> list[dict]:
        """Accelerator inventory (TPU-native extension)."""
        try:
            import jax

            return [
                {
                    "id": d.id,
                    "kind": d.device_kind,
                    "platform": d.platform,
                    "process": d.process_index,
                }
                for d in jax.devices()
            ]
        except Exception:
            return []

    def to_dict(self) -> dict:
        m = self._meminfo()
        total = m.get("MemTotal", 0)
        free = m.get("MemAvailable", m.get("MemFree", 0))
        return {
            "uptime": self.uptime(),
            "platform": self.platform(),
            "family": self.family(),
            "osVersion": self.os_version(),
            "kernelVersion": self.kernel_version(),
            "memTotal": total,
            "memFree": free,
            "memUsed": total - free if total else 0,
            "cpuCount": self.cpu_count(),
            "threadCount": self.thread_count(),
            "processRSS": self.process_rss(),
            "devices": self.devices(),
        }


class GCNotifier:
    """GC → stats bridge (reference gcnotify/ + server.go:826-833:
    a channel that ticks after every garbage collection, counted into
    the stats client). Uses CPython's gc callback hook.

    The callback itself only bumps a bare int: CPython invokes
    gc.callbacks synchronously on WHATEVER thread triggered collection,
    possibly while that thread already holds the stats client's
    non-reentrant lock (e.g. mid-snapshot) — calling into the client
    here would self-deadlock. RuntimeMonitor publishes the counter as a
    gauge instead.

    gc.callbacks is process-global, so the registered hook holds only a
    weakref: a notifier dropped without close() unregisters itself on the
    next collection instead of pinning its owner for the process
    lifetime."""

    def __init__(self):
        import gc
        import weakref

        self._gc = gc
        self.collections = 0

        ref = weakref.ref(self)

        def _cb(phase: str, info: dict, _ref=ref, _gc=gc) -> None:
            self_ = _ref()
            if self_ is None:
                try:
                    _gc.callbacks.remove(_cb)
                except ValueError:
                    pass
                return
            if phase == "stop":
                self_.collections += 1  # plain int bump: no locks, no allocation

        self._cb = _cb
        gc.callbacks.append(_cb)

    def close(self) -> None:
        try:
            self._gc.callbacks.remove(self._cb)
        except ValueError:
            pass


class RuntimeMonitor:
    """Periodic runtime-metrics gauge loop (reference server.go:793-835
    monitorRuntime: heap/goroutines/open-files into stats)."""

    def __init__(self, stats_client, interval: float = 10.0, gc_notifier=None):
        self.stats = stats_client
        self.interval = interval
        self.gc_notifier = gc_notifier
        self.info = SystemInfo()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def poll_once(self) -> None:
        self.stats.gauge("memory_rss_bytes", self.info.process_rss())
        self.stats.gauge("threads", self.info.thread_count())
        self.stats.gauge("host_mem_free_bytes", self.info.mem_free())
        self.stats.gauge(
            "process_uptime_seconds", round(self.info.process_uptime(), 3)
        )
        self.stats.gauge(
            "process_start_time_seconds", self.info.process_start_time()
        )
        if self.gc_notifier is not None:
            self.stats.gauge("garbage_collections", self.gc_notifier.collections)

    def start(self) -> None:
        def run():
            while not self._stop.wait(self.interval):
                try:
                    self.poll_once()
                except Exception:
                    # keep polling; a failed sample is itself a metric
                    self.stats.count("metric_poll_errors", 1)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
