"""Crash-durable black box: a bounded on-disk spool of the perishable
observability planes, plus startup postmortem assembly.

Every other observability surface (flight-recorder segments, incident
bundles, trend episodes, history rings, kept traces, event journal,
SLO/QoS/devledger snapshots) is in-memory: a SIGKILL, OOM, or segfault
takes the evidence with it — which is exactly the evidence an operator
needs most.  GWP/Dapper practice treats durable, restart-readable
diagnostics as table stakes; Go Pilosa persists its diagnostics
payloads for the same reason.

Shape:

* A low-rate writer thread checkpoints the *tails* of the live planes
  into atomic segment files under ``<data_dir>/_blackbox/`` — written
  as ``.tmp`` + fsync + ``os.replace`` so a crash mid-write leaves the
  previous segment intact, never a torn one (torn files from a crash
  mid-``write`` of the tmp are skipped at assembly, counted, and
  reported — not fatal).
* Incident fire triggers a synchronous flush (the flight recorder's
  ``on_incident`` hook), so the frozen bundle reaches disk the moment
  it exists rather than up to one interval later.
* ``faulthandler`` is pointed at a ``last-words.txt`` in the spool so
  fatal signals (SEGV/ABRT/BUS/FPE/ILL) dump all-thread stacks into
  the black box on the way down.
* A ``STATUS`` marker records ``running`` while alive and ``clean`` on
  orderly shutdown (``close()``/SIGTERM/atexit).  On the next open, a
  ``running`` marker means the previous life died dirty: the spool is
  sealed into a read-only postmortem bundle (served at ``GET
  /debug/postmortem``), a crash-loop counter is incremented, and a
  ``node-crash-detected`` event is journaled.  A ``clean`` marker
  resets the crash-loop counter and discards the stale spool.

The spool is size- and count-capped (oldest segments deleted first) so
the black box can never eat the data dir, and everything here is
best-effort: a failing checkpoint must never take down the serving
process it is trying to explain.
"""

from __future__ import annotations

import atexit
import faulthandler
import json
import math
import os
import signal
import sys
import threading
import time

from pilosa_tpu.obs import events as ev

_STATUS_FILE = "STATUS"
_CRASHLOOP_FILE = "CRASHLOOP"
_LASTWORDS_FILE = "last-words.txt"
_SEG_PREFIX = "seg-"
_PM_PREFIX = "postmortem-"

# events carried per checkpoint segment (deduped by seq at assembly)
_EVENT_TAIL = 256


def _atomic_write(path: str, data: bytes) -> None:
    """Write-temp + fsync + rename: the file at ``path`` is always a
    complete previous or complete new version, never a torn mix."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _atomic_write_json(path: str, obj) -> int:
    data = json.dumps(obj, default=str).encode()
    _atomic_write(path, data)
    return len(data)


def _read_json(path: str):
    """None on missing, torn, or unreadable — the caller counts torn
    files; a half-written segment must never abort assembly."""
    try:
        with open(path, "rb") as f:
            return json.loads(f.read())
    except (OSError, ValueError):
        return None


class BlackBox:
    """Bounded crash-durable spool + postmortem assembler for one node."""

    def __init__(
        self,
        holder,
        data_dir: str,
        api=None,
        flightrec=None,
        history=None,
        node_id: str = "",
        interval: float = 5.0,
        max_segments: int = 64,
        max_bytes: int = 16 << 20,
        keep_postmortems: int = 4,
        history_window: float = 60.0,
    ):
        self.holder = holder
        self.api = api
        self.flightrec = flightrec
        self.history = history
        self.node_id = node_id
        self.dir = os.path.join(data_dir, "_blackbox")
        self.interval = max(0.05, float(interval))
        self.max_segments = max(1, int(max_segments))
        self.max_bytes = max(1 << 16, int(max_bytes))
        self.keep_postmortems = max(1, int(keep_postmortems))
        self.history_window = max(1.0, float(history_window))
        self.started_at = time.time()
        self._lock = threading.Lock()
        self._seq = 0
        self._closed = False
        self._owns_faulthandler = False
        self._lw_file = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._stats = {
            "checkpoints": 0,
            "checkpointSeconds": 0.0,
            "syncFlushes": 0,
            "torn": 0,
            "crashLoop": 0,
        }

    # -- lifecycle -----------------------------------------------------------

    def open(self) -> dict | None:
        """Inspect the previous life's spool, seal a postmortem if it
        died dirty, then arm this life's marker + faulthandler + atexit.
        Returns the assembled postmortem (already persisted) or None."""
        os.makedirs(self.dir, exist_ok=True)
        status = _read_json(os.path.join(self.dir, _STATUS_FILE))
        dirty = bool(status) and status.get("state") == "running"
        postmortem = None
        if dirty:
            postmortem = self._assemble_postmortem(status)
        else:
            self._reset_crashloop()
            self._discard_segments()
        _atomic_write_json(
            os.path.join(self.dir, _STATUS_FILE),
            {
                "state": "running",
                "pid": os.getpid(),
                "node": self.node_id,
                "startedAt": self.started_at,
            },
        )
        self._arm_faulthandler()
        atexit.register(self._atexit)
        if postmortem is not None:
            self._journal_crash(postmortem)
        return postmortem

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="blackbox-writer", daemon=True
        )
        self._thread.start()

    def close(self, clean: bool = True) -> None:
        """Stop the writer, take one final checkpoint, and (when
        ``clean``) replace the dirty marker with a clean one so the next
        life knows this was an orderly shutdown, not a crash."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None
        try:
            self.checkpoint("shutdown")
        except Exception:  # graftlint: disable=exception-hygiene -- a failing final checkpoint must not block shutdown
            pass
        if clean:
            try:
                _atomic_write_json(
                    os.path.join(self.dir, _STATUS_FILE),
                    {
                        "state": "clean",
                        "pid": os.getpid(),
                        "node": self.node_id,
                        "startedAt": self.started_at,
                        "stoppedAt": time.time(),
                    },
                )
            except OSError:
                pass
        try:
            atexit.unregister(self._atexit)
        except Exception:  # graftlint: disable=exception-hygiene -- interpreter teardown may have dropped the registry
            pass
        self._disarm_faulthandler()

    def _atexit(self) -> None:
        # Interpreter exit without close() (e.g. sys.exit from a signal
        # handler that raced the graceful path): still an orderly death.
        if not self._closed:
            self.close(clean=True)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.checkpoint("interval")
            except Exception:  # graftlint: disable=exception-hygiene -- the black box must outlive any one bad checkpoint
                pass

    # -- faulthandler (last words) -------------------------------------------

    def _arm_faulthandler(self) -> None:
        global _FAULTHANDLER_OWNER
        with _FH_LOCK:
            if _FAULTHANDLER_OWNER is not None:
                return  # another node in this process already owns it
            try:
                f = open(  # noqa: SIM115 -- must outlive this frame for faulthandler
                    os.path.join(self.dir, _LASTWORDS_FILE), "w"
                )
                faulthandler.enable(file=f, all_threads=True)
            except (OSError, ValueError):
                return
            self._lw_file = f
            self._owns_faulthandler = True
            _FAULTHANDLER_OWNER = id(self)

    def _disarm_faulthandler(self) -> None:
        global _FAULTHANDLER_OWNER
        with _FH_LOCK:
            if not self._owns_faulthandler:
                return
            try:
                faulthandler.disable()
            except Exception:  # graftlint: disable=exception-hygiene -- already-disabled is fine
                pass
            if self._lw_file is not None:
                try:
                    self._lw_file.close()
                except OSError:
                    pass
                self._lw_file = None
            self._owns_faulthandler = False
            _FAULTHANDLER_OWNER = None

    # -- checkpointing -------------------------------------------------------

    def flush_incident(self, bundle=None) -> None:
        """Flight-recorder ``on_incident`` hook: the frozen bundle must
        reach disk NOW, not up to one interval later — an incident is
        precisely the moment the process is likeliest to die next."""
        try:
            with self._lock:
                self._stats["syncFlushes"] += 1
            self.checkpoint("incident")
        except Exception:  # graftlint: disable=exception-hygiene -- a failed flush must not reach the incident engine
            pass

    def checkpoint(self, reason: str = "interval") -> None:
        """Collect the perishable tails of every plane (no blackbox lock
        held — plane locks are taken by the planes themselves) and write
        one atomic segment file, then enforce the spool caps."""
        t0 = time.monotonic()
        seg = self._collect(reason)
        with self._lock:
            if self._closed and reason != "shutdown":
                return
            self._seq += 1
            seg["seq"] = self._seq
            path = os.path.join(
                self.dir, f"{_SEG_PREFIX}{self._seq:08d}.json"
            )
            _atomic_write_json(path, seg)
            self._enforce_caps()
            self._stats["checkpoints"] += 1
            self._stats["checkpointSeconds"] += time.monotonic() - t0

    def _collect(self, reason: str) -> dict:
        seg: dict = {
            "at": time.time(),
            "reason": reason,
            "node": self.node_id,
            "pid": os.getpid(),
        }
        fr = self.flightrec
        if fr is not None:
            try:
                seg["flightrec"] = {
                    "segments": fr.segments_snapshot(limit=10),
                    "incidents": fr.incidents_full(),
                }
            except Exception:  # graftlint: disable=exception-hygiene -- one plane failing must not starve the others
                pass
        hist = self.history
        if hist is not None:
            try:
                seg["history"] = hist.blackbox_snapshot(self.history_window)
            except Exception:  # graftlint: disable=exception-hygiene -- one plane failing must not starve the others
                pass
        traces = getattr(self.holder, "traces", None)
        if traces is not None:
            try:
                seg["traces"] = traces.blackbox_snapshot()
            except Exception:  # graftlint: disable=exception-hygiene -- one plane failing must not starve the others
                pass
        journal = getattr(self.holder, "events", None)
        if journal is not None:
            try:
                tail = journal.since(
                    max(0, journal.last_seq - _EVENT_TAIL)
                )
                seg["events"] = tail["events"]
            except Exception:  # graftlint: disable=exception-hygiene -- one plane failing must not starve the others
                pass
        slo = getattr(self.holder, "slo", None)
        if slo is not None:
            try:
                seg["slo"] = {
                    "snapshot": slo.snapshot(),
                    "pressure": slo.pressure(),
                }
            except Exception:  # graftlint: disable=exception-hygiene -- one plane failing must not starve the others
                pass
        api = self.api
        qos = getattr(api, "qos", None) if api is not None else None
        if qos is not None:
            try:
                seg["qos"] = qos.snapshot()
            except Exception:  # graftlint: disable=exception-hygiene -- one plane failing must not starve the others
                pass
        try:
            from pilosa_tpu.obs import devledger

            seg["devledger"] = devledger.counters()
        except Exception:  # graftlint: disable=exception-hygiene -- ledger snapshots are advisory
            pass
        return seg

    def _seg_files(self) -> list[str]:
        try:
            names = sorted(
                n for n in os.listdir(self.dir)
                if n.startswith(_SEG_PREFIX) and n.endswith(".json")
            )
        except OSError:
            return []
        return [os.path.join(self.dir, n) for n in names]

    def _enforce_caps(self) -> None:
        """Delete oldest segments past the count/byte caps (the newest
        segment always survives — a cap must bound the spool, not blind
        it)."""
        files = self._seg_files()
        sizes = []
        for p in files:
            try:
                sizes.append(os.path.getsize(p))
            except OSError:
                sizes.append(0)
        total = sum(sizes)
        i = 0
        while len(files) - i > 1 and (
            len(files) - i > self.max_segments or total > self.max_bytes
        ):
            try:
                os.remove(files[i])
            except OSError:
                pass
            total -= sizes[i]
            i += 1

    # -- postmortem assembly -------------------------------------------------

    def _assemble_postmortem(self, status: dict) -> dict:
        """Seal the dead life's spool into one read-only bundle: dedupe
        flight-recorder segments by seq, incidents by id, events by
        seq; keep the LAST history/traces/SLO/QoS/devledger blocks
        (they are cumulative snapshots, not deltas); attach the
        last-words stack dump and the crash-loop counter."""
        torn = 0
        segs: list[dict] = []
        for path in self._seg_files():
            obj = _read_json(path)
            if obj is None:
                torn += 1
                continue
            segs.append(obj)
        fr_segs: dict = {}
        incidents: dict = {}
        events: dict = {}
        last: dict = {}
        for seg in segs:
            for s in (seg.get("flightrec") or {}).get("segments", []):
                fr_segs[s.get("seq")] = s
            for b in (seg.get("flightrec") or {}).get("incidents", []):
                incidents[b.get("id")] = b
            for e in seg.get("events", []):
                events[e.get("seq")] = e
            for key in ("history", "traces", "slo", "qos", "devledger"):
                if seg.get(key) is not None:
                    last[key] = seg[key]
        last_words = None
        try:
            with open(os.path.join(self.dir, _LASTWORDS_FILE)) as f:
                text = f.read().strip()
            last_words = text or None
        except OSError:
            pass
        crash_loop = self._bump_crashloop()
        pid = status.get("pid")
        started = status.get("startedAt")
        pm_id = (
            f"{int(started)}-{pid}"
            if isinstance(started, (int, float)) and pid is not None
            else f"{int(time.time())}-unknown"
        )
        bundle = {
            "id": pm_id,
            "assembledAt": time.time(),
            "node": status.get("node", ""),
            "pid": pid,
            "startedAt": started,
            "lastCheckpointAt": segs[-1]["at"] if segs else None,
            "crashLoop": crash_loop,
            "lastWords": last_words,
            "segments": len(segs),
            "torn": torn,
            "incidents": sorted(
                incidents.values(), key=lambda b: b.get("at", 0.0)
            ),
            "flightrecSegments": [
                fr_segs[k] for k in sorted(fr_segs, key=lambda s: s or 0)
            ],
            "events": [
                events[k] for k in sorted(events, key=lambda s: s or 0)
            ],
            "history": last.get("history"),
            "traces": last.get("traces"),
            "slo": last.get("slo"),
            "qos": last.get("qos"),
            "devledger": last.get("devledger"),
        }
        with self._lock:
            self._stats["torn"] += torn
            self._stats["crashLoop"] = crash_loop
        try:
            _atomic_write_json(
                os.path.join(self.dir, f"{_PM_PREFIX}{pm_id}.json"), bundle
            )
        except OSError:
            pass
        self._discard_segments()
        self._prune_postmortems()
        return bundle

    def _journal_crash(self, postmortem: dict) -> None:
        journal = getattr(self.holder, "events", None)
        if journal is None:
            return
        try:
            journal.record(
                ev.EVENT_NODE_CRASH,
                postmortem=postmortem["id"],
                crashLoop=postmortem["crashLoop"],
                pid=postmortem.get("pid"),
                lastWords=bool(postmortem.get("lastWords")),
                incidents=len(postmortem.get("incidents") or ()),
            )
        except Exception:  # graftlint: disable=exception-hygiene -- journaling is best-effort
            pass

    def _discard_segments(self) -> None:
        for path in self._seg_files():
            try:
                os.remove(path)
            except OSError:
                pass

    def _bump_crashloop(self) -> int:
        path = os.path.join(self.dir, _CRASHLOOP_FILE)
        prev = _read_json(path) or {}
        count = int(prev.get("count", 0)) + 1
        try:
            _atomic_write_json(
                path, {"count": count, "lastCrashAt": time.time()}
            )
        except OSError:
            pass
        return count

    def _reset_crashloop(self) -> None:
        path = os.path.join(self.dir, _CRASHLOOP_FILE)
        if _read_json(path) is not None:
            try:
                _atomic_write_json(path, {"count": 0, "lastCrashAt": None})
            except OSError:
                pass

    def _pm_files(self) -> list[tuple[str, str]]:
        """[(id, path)] for sealed bundles, oldest assembly first."""
        try:
            names = [
                n for n in os.listdir(self.dir)
                if n.startswith(_PM_PREFIX) and n.endswith(".json")
            ]
        except OSError:
            return []
        out = []
        for n in names:
            pm_id = n[len(_PM_PREFIX):-len(".json")]
            path = os.path.join(self.dir, n)
            obj = _read_json(path)
            at = (obj or {}).get("assembledAt", 0.0)
            out.append((at, pm_id, path))
        out.sort()
        return [(pm_id, path) for _, pm_id, path in out]

    def _prune_postmortems(self) -> None:
        files = self._pm_files()
        for _, path in files[: max(0, len(files) - self.keep_postmortems)]:
            try:
                os.remove(path)
            except OSError:
                pass

    # -- exposition ----------------------------------------------------------

    def postmortems(self) -> dict:
        """``GET /debug/postmortem``: summaries of every retained bundle
        (newest first) plus the newest bundle in full — the acceptance
        surface after a crash is one GET, no id juggling."""
        files = self._pm_files()
        summaries = []
        latest = None
        for pm_id, path in files:
            obj = _read_json(path)
            if obj is None:
                continue
            latest = obj
            summaries.append({
                k: obj.get(k)
                for k in (
                    "id", "assembledAt", "node", "pid", "startedAt",
                    "lastCheckpointAt", "crashLoop", "segments", "torn",
                )
            } | {
                "incidents": len(obj.get("incidents") or ()),
                "lastWords": bool(obj.get("lastWords")),
            })
        summaries.reverse()
        return {
            "node": self.node_id,
            "postmortems": summaries,
            "latest": summaries[0]["id"] if summaries else None,
            "postmortem": latest,
        }

    def postmortem_detail(self, pm_id: str) -> dict | None:
        for got, path in self._pm_files():
            if got == pm_id:
                return _read_json(path)
        return None

    def stats(self) -> dict:
        """Writer self-accounting for /debug/vars and the bench lane."""
        with self._lock:
            out = dict(self._stats)
        out["interval"] = self.interval
        out["maxSegments"] = self.max_segments
        out["maxBytes"] = self.max_bytes
        files = self._seg_files()
        out["segments"] = len(files)
        total = 0
        for p in files:
            try:
                total += os.path.getsize(p)
            except OSError:
                pass
        out["bytes"] = total
        out["postmortems"] = len(self._pm_files())
        out["checkpointSeconds"] = round(out["checkpointSeconds"], 6)
        return out


# -- process-wide fatal-signal / shutdown plumbing ---------------------------

_FH_LOCK = threading.Lock()
_FAULTHANDLER_OWNER: int | None = None

_SIG_LOCK = threading.Lock()
_SIG_NODES: list = []
_SIG_INSTALLED = False


def _handle_sigterm(signum, frame) -> None:
    """Drain every registered node, then exit 0: SIGTERM is an orderly
    stop, and must not read as a crash on the next boot."""
    for node in list(_SIG_NODES):
        try:
            node.shutdown_graceful()
        except Exception:  # graftlint: disable=exception-hygiene -- one node's failed drain must not stop the others'
            pass
    sys.exit(0)


def install_signal_handlers(node) -> bool:
    """Register ``node`` for graceful SIGTERM shutdown.  Installs the
    process-wide handler on first call; returns False when handlers
    cannot be installed (non-main thread — in-process test clusters
    boot nodes from worker threads and handle lifecycle themselves)."""
    global _SIG_INSTALLED
    with _SIG_LOCK:
        if node not in _SIG_NODES:
            _SIG_NODES.append(node)
        if _SIG_INSTALLED:
            return True
        try:
            signal.signal(signal.SIGTERM, _handle_sigterm)
        except ValueError:
            _SIG_NODES.remove(node)
            return False
        _SIG_INSTALLED = True
        return True


def uninstall_signal_handlers(node) -> None:
    with _SIG_LOCK:
        if node in _SIG_NODES:
            _SIG_NODES.remove(node)


def history_window_samples(window_s: float, cadence: float) -> int:
    """Samples needed to cover ``window_s`` at ``cadence`` (ceil)."""
    return max(1, int(math.ceil(float(window_s) / max(1e-6, cadence))))
