"""Stats client (reference: stats/stats.go:31-65 StatsClient interface).

The reference defines a small tagged-metrics interface with pluggable
backends — expvar (stats/stats.go:84+), statsd/DataDog (statsd/statsd.go:48)
and Prometheus (prometheus/prometheus.go:52) — selected by the
``metric.service`` config key (server/server.go:397-411), with
``NopStatsClient`` as the zero default so instrumented code never
nil-checks.

Here the in-memory :class:`MemStatsClient` doubles as the expvar backend
(``/debug/vars`` JSON dump) and the Prometheus backend (text exposition via
:func:`prometheus_text`, served at ``/metrics`` — reference
http/handler.go:282). statsd wire output is out of scope (no egress), but
the interface point where it would plug in is the same.
"""

from __future__ import annotations

import threading
import time
from typing import Iterable

from pilosa_tpu.obs import tracing


def _ambient_trace_id() -> str | None:
    """The active span's trace id (32-hex) — the exemplar candidate a
    histogram observation records for its bucket."""
    span = tracing.active_span()
    if span is None:
        return None
    return f"{span.context.trace_id & (2**128 - 1):032x}"


class StatsClient:
    """Tagged metrics interface (reference stats/stats.go:31-65)."""

    def with_tags(self, *tags: str) -> "StatsClient":
        return self

    def count(self, name: str, value: int = 1, rate: float = 1.0) -> None:
        raise NotImplementedError

    def count_with_tags(
        self, name: str, value: int, rate: float, tags: Iterable[str]
    ) -> None:
        raise NotImplementedError

    def gauge(self, name: str, value: float) -> None:
        raise NotImplementedError

    def histogram(self, name: str, value: float) -> None:
        raise NotImplementedError

    def set_value(self, name: str, value: str) -> None:
        raise NotImplementedError

    def timing(self, name: str, seconds: float) -> None:
        raise NotImplementedError


class NopStatsClient(StatsClient):
    """Zero-cost default (reference stats.NopStatsClient)."""

    def count(self, name, value=1, rate=1.0):
        pass

    def count_with_tags(self, name, value, rate, tags):
        pass

    def gauge(self, name, value):
        pass

    def histogram(self, name, value):
        pass

    def set_value(self, name, value):
        pass

    def timing(self, name, seconds):
        pass


NOP = NopStatsClient()


# Prometheus-style cumulative bucket bounds.  Log-spaced seconds: the
# sub-ms bounds (50/100/250/500 µs) resolve the measured serving-cache
# floor of 0.07-0.16 ms/op (BENCH_r05) — without them every read-path
# latency collapses into the first bucket and p999 is meaningless — and
# the top end still covers multi-second cluster queries.
HISTOGRAM_BUCKETS = (
    0.00005, 0.0001, 0.00025, 0.0005,
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class _Histo:
    __slots__ = ("count", "total", "min", "max", "buckets", "exemplars")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.buckets = [0] * len(HISTOGRAM_BUCKETS)
        # per-bucket exemplar candidate (trace_id_hex, value, unix_ts);
        # index len(HISTOGRAM_BUCKETS) is the +Inf bucket.  "Candidate"
        # because keep/drop is the trace store's tail decision — the
        # renderer filters against the kept set at scrape time.
        self.exemplars: list[tuple[str, float, float] | None] = [None] * (
            len(HISTOGRAM_BUCKETS) + 1
        )

    def observe(self, v: float, trace_id: str | None = None) -> None:
        self.count += 1
        self.total += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        tight = len(HISTOGRAM_BUCKETS)  # +Inf unless a bound catches v
        for i, bound in enumerate(HISTOGRAM_BUCKETS):
            if v <= bound:
                self.buckets[i] += 1
                if i < tight:
                    tight = i
        if trace_id is not None:
            # tightest bucket only (OpenMetrics: one exemplar per bucket)
            self.exemplars[tight] = (trace_id, v, time.time())

    def to_dict(self) -> dict:
        buckets = {
            str(b): c for b, c in zip(HISTOGRAM_BUCKETS, self.buckets)
        }
        # Cumulative +Inf bucket: observations above the largest bound
        # land only here, so the bucket map always sums to count.
        buckets["+Inf"] = self.count
        return {
            "count": self.count,
            "sum": self.total,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
            "buckets": buckets,
        }


class MemStatsClient(StatsClient):
    """Thread-safe in-memory aggregator; the expvar/prometheus backend.

    Tag handling mirrors the reference's Prometheus backend, which turns
    ``"index:foo"`` tags into ``{index="foo"}`` labels
    (prometheus/prometheus.go:52+). Keys are (name, sorted-tags).
    """

    def __init__(self, tags: tuple[str, ...] = ()):
        self._lock = threading.Lock()
        self._tags = tuple(sorted(tags))
        # shared across with_tags children
        self._counters: dict[tuple[str, tuple[str, ...]], float] = {}
        self._gauges: dict[tuple[str, tuple[str, ...]], float] = {}
        self._histograms: dict[tuple[str, tuple[str, ...]], _Histo] = {}
        self._sets: dict[tuple[str, tuple[str, ...]], set[str]] = {}

    def with_tags(self, *tags: str) -> "MemStatsClient":
        child = MemStatsClient.__new__(MemStatsClient)
        child._lock = self._lock
        child._tags = tuple(sorted(set(self._tags) | set(tags)))
        child._counters = self._counters
        child._gauges = self._gauges
        child._histograms = self._histograms
        child._sets = self._sets
        return child

    def _key(self, name: str, extra: Iterable[str] = ()) -> tuple[str, tuple[str, ...]]:
        if extra:
            return name, tuple(sorted(set(self._tags) | set(extra)))
        return name, self._tags

    def count(self, name, value=1, rate=1.0):
        k = self._key(name)
        with self._lock:
            self._counters[k] = self._counters.get(k, 0) + value

    def count_with_tags(self, name, value, rate, tags):
        k = self._key(name, tags)
        with self._lock:
            self._counters[k] = self._counters.get(k, 0) + value

    def gauge(self, name, value):
        with self._lock:
            self._gauges[self._key(name)] = value

    def histogram(self, name, value):
        k = self._key(name)
        trace_id = _ambient_trace_id()
        with self._lock:
            h = self._histograms.get(k)
            if h is None:
                h = self._histograms[k] = _Histo()
            h.observe(value, trace_id)

    def get_counter(self, name: str, tags: Iterable[str] = ()) -> float:
        """Current value of one counter (0.0 when never incremented) —
        the flight recorder diffs these per segment."""
        k = self._key(name, tags)
        with self._lock:
            return self._counters.get(k, 0)

    def set_value(self, name, value):
        k = self._key(name)
        with self._lock:
            self._sets.setdefault(k, set()).add(value)

    def timing(self, name, seconds):
        self.histogram(name + "_seconds", seconds)

    # -- exposition ---------------------------------------------------------

    def snapshot(self) -> dict:
        """expvar-style JSON dump (reference ``/debug/vars``)."""

        def label(k):
            name, tags = k
            return name if not tags else name + "{" + ",".join(tags) + "}"

        with self._lock:
            return {
                "counters": {label(k): v for k, v in self._counters.items()},
                "gauges": {label(k): v for k, v in self._gauges.items()},
                "histograms": {
                    label(k): h.to_dict() for k, h in self._histograms.items()
                },
                "sets": {label(k): len(s) for k, s in self._sets.items()},
            }


class StatsDClient(StatsClient):
    """UDP statsd/DataDog backend (reference statsd/statsd.go:48 — the
    DataDog dogstatsd client with tag support, selected by
    ``metric.service = "statsd"``/``"datadog"``).

    Wire format per datagram: ``pilosa.<name>:<value>|<type>[|@rate][|#tags]``
    — counters ``c``, gauges ``g``, histograms/timings ``h``/``ms``,
    sets ``s``.  Fire-and-forget: send failures are swallowed (a
    metrics sink must never take the server down), matching the
    reference client's behavior."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8125,
        prefix: str = "pilosa.",
        tags: tuple[str, ...] = (),
    ):
        import socket

        self._addr = (host, port)
        self._prefix = prefix
        self._tags = tuple(tags)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setblocking(False)

    def with_tags(self, *tags: str) -> "StatsDClient":
        child = object.__new__(StatsDClient)
        child._addr = self._addr
        child._prefix = self._prefix
        child._sock = self._sock
        child._tags = self._tags + tuple(tags)
        return child

    def _send(
        self, name: str, value, typ: str, rate: float = 1.0,
        tags: Iterable[str] = (),
    ) -> None:
        msg = f"{self._prefix}{name}:{value}|{typ}"
        if rate != 1.0:
            msg += f"|@{rate}"
        all_tags = self._tags + tuple(tags)
        if all_tags:
            msg += "|#" + ",".join(all_tags)
        try:
            self._sock.sendto(msg.encode(), self._addr)
        except OSError:
            pass  # fire-and-forget

    def count(self, name, value=1, rate=1.0):
        self._send(name, value, "c", rate)

    def count_with_tags(self, name, value, rate, tags):
        self._send(name, value, "c", rate, tags)

    def gauge(self, name, value):
        self._send(name, value, "g")

    def histogram(self, name, value):
        self._send(name, value, "h")

    def set_value(self, name, value):
        self._send(name, value, "s")

    def timing(self, name, seconds):
        self._send(name, round(seconds * 1e3, 3), "ms")

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _prom_name(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _prom_escape(value: str) -> str:
    """Escape a label VALUE per the Prometheus text exposition spec:
    backslash, double-quote, and line-feed.  Tenant/index names are
    user-controlled, so a hostile ``evil"} 1`` tenant must not be able
    to forge metric lines or break strict scrapers."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _prom_labels(tags: tuple[str, ...]) -> str:
    if not tags:
        return ""
    parts = []
    for t in tags:
        k, _, v = t.partition(":")
        parts.append(f'{_prom_name(k)}="{_prom_escape(v)}"')
    return "{" + ",".join(parts) + "}"


def _prom_le_labels(tags: tuple[str, ...], bound) -> str:
    """Labels with the histogram ``le`` bucket bound merged in."""
    parts = []
    for t in tags:
        k, _, v = t.partition(":")
        parts.append(f'{_prom_name(k)}="{_prom_escape(v)}"')
    parts.append(f'le="{bound}"')
    return "{" + ",".join(parts) + "}"


# -- metric descriptions (# HELP) -------------------------------------------
#
# Registry keyed by the EXPOSED metric name (after the pilosa_ prefix
# and name mangling).  prometheus_text emits "# HELP" only for metrics
# registered here, immediately before the "# TYPE" line, so unregistered
# families keep byte-identical output.
_HELP: dict[str, str] = {}
_HELP_LOCK = threading.Lock()


def describe(name: str, text: str) -> None:
    """Register a one-line description for an exposed metric family
    (e.g. ``describe("pilosa_set_bit", "bits set via PQL Set()")``)."""
    with _HELP_LOCK:
        _HELP[name] = str(text)


def _help_escape(text: str) -> str:
    # HELP text escapes backslash and line-feed only (quotes are legal)
    return text.replace("\\", "\\\\").replace("\n", "\\n")


describe("pilosa_set_bit", "bits set via PQL Set() writes")
describe("pilosa_clear_bit", "bits cleared via PQL Clear() writes")
describe("pilosa_query_durationSeconds",
         "end-to-end PQL query latency through the executor")
describe("pilosa_http_request_durationSeconds",
         "HTTP request latency by route")
describe("pilosa_http_deadline_exceeded",
         "requests that ran out of deadline budget (504)")
describe("pilosa_serving_cache_hit",
         "warm repeat reads answered from the per-snapshot host cache")
describe("pilosa_batcher_depth", "queued requests inside the micro-batcher")
describe("pilosa_slo_error_budget_burn_rate",
         "per-class SRE multi-window error-budget burn rate")
describe("pilosa_dev_device_ms",
         "measured on-device milliseconds from the device cost ledger")
describe("pilosa_qos_shed_total",
         "requests shed (429) by the cost-governed admission ladder")
describe("pilosa_history_samples",
         "metrics-history sampler ticks recorded into the ring TSDB")
describe("pilosa_history_trend_incidents",
         "trend-detector incidents fired through the flight recorder")


def exemplar_suffix(
    ex: tuple[str, float, float] | None, exemplar_filter
) -> str:
    """OpenMetrics exemplar suffix for one bucket line, or "" — only
    exemplars whose trace survived tail sampling are exposed (the filter
    is membership in the trace store's kept set).  ``None`` filter means
    exemplars are off (plain exposition, the pre-exemplar output)."""
    if ex is None or exemplar_filter is None:
        return ""
    trace_id, value, ts = ex
    if not exemplar_filter(trace_id):
        return ""
    return f' # {{trace_id="{trace_id}"}} {value} {round(ts, 3)}'


def prometheus_text(client: StatsClient, exemplar_filter=None) -> str:
    """Render a MemStatsClient in Prometheus text exposition format
    (reference prometheus/prometheus.go:52, route http/handler.go:282).
    With ``exemplar_filter`` (a trace-id predicate), histogram bucket
    lines carry OpenMetrics ``# {trace_id="..."}`` exemplars for kept
    traces, so an operator jumps from a latency bucket straight to
    ``/debug/traces?id=``."""
    if not isinstance(client, MemStatsClient):
        return ""
    out: list[str] = []
    with client._lock:
        counters = dict(client._counters)
        gauges = dict(client._gauges)
        histos = {
            k: (h.count, h.total, list(h.buckets), list(h.exemplars))
            for k, h in client._histograms.items()
        }
        sets = {k: len(s) for k, s in client._sets.items()}
    seen: set[str] = set()

    with _HELP_LOCK:
        helps = dict(_HELP)

    def typ(name: str, t: str) -> None:
        if name not in seen:
            seen.add(name)
            h = helps.get(name)
            if h is not None:
                out.append(f"# HELP {name} {_help_escape(h)}")
            out.append(f"# TYPE {name} {t}")

    for (name, tags), v in sorted(counters.items()):
        n = "pilosa_" + _prom_name(name)
        typ(n, "counter")
        out.append(f"{n}{_prom_labels(tags)} {v}")
    for (name, tags), v in sorted(gauges.items()):
        n = "pilosa_" + _prom_name(name)
        typ(n, "gauge")
        out.append(f"{n}{_prom_labels(tags)} {v}")
    for (name, tags), (cnt, total, buckets, exemplars) in sorted(
        histos.items()
    ):
        n = "pilosa_" + _prom_name(name)
        typ(n, "histogram")
        for i, (bound, bcnt) in enumerate(zip(HISTOGRAM_BUCKETS, buckets)):
            ex = exemplar_suffix(exemplars[i], exemplar_filter)
            out.append(f"{n}_bucket{_prom_le_labels(tags, bound)} {bcnt}{ex}")
        ex = exemplar_suffix(exemplars[-1], exemplar_filter)
        out.append(f'{n}_bucket{_prom_le_labels(tags, "+Inf")} {cnt}{ex}')
        out.append(f"{n}_count{_prom_labels(tags)} {cnt}")
        out.append(f"{n}_sum{_prom_labels(tags)} {total}")
    for (name, tags), card in sorted(sets.items()):
        n = "pilosa_" + _prom_name(name) + "_cardinality"
        typ(n, "gauge")
        out.append(f"{n}{_prom_labels(tags)} {card}")
    return "\n".join(out) + "\n"
