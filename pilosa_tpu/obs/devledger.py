"""Process-wide device cost ledger: compile / launch / transfer accounting.

Every jit or kernel launch site in the tree registers a :class:`Site`
(``ledger.site("executor.stack_launch")``) and reports through it, so the
server can answer two questions the rest of the observability plane cannot:

* **what did the device work cost** — XLA compile count and wall-time
  (new-compile vs cache-hit), launch counts and wall/device time, H2D/D2H
  bytes, and (opt-in) ``cost_analysis()`` FLOPs/bytes per compiled program;
* **who caused it** — attribution along two axes: the *site* (which launch
  path) and the *principal* ``(tenant, index, op_class)``, with the tenant
  read from the ``X-Pilosa-Tenant`` request header and threaded
  http → api → batcher → executor via a contextvar (default tenant ``"-"``).

Compile detection rides ``jax.monitoring``: a cache-hit jit call emits no
events, while a real XLA compile emits ``backend_compile_duration`` exactly
once (plus trace/lowering durations), synchronously in the calling thread.
The listener attributes each event to the innermost active *launch window*
(``with site.launch(sig=...)``) on that thread; sites that report after the
fact (the ops.kernels dispatch funnel) claim the thread's stashed events
instead.  A **recompile-storm detector** (>= N new compiles inside a sliding
window, after warmup) freezes the offending sites/shapes into a bundle and
fans out to registered callbacks (the node wires this to the flight
recorder's incident engine).

The ledger is process-global by design — compile caches and devices are
process-global — matching the precedent of ``kernels.kernel_stats`` and the
residency/membudget singletons.  ``reset()`` exists for tests and benches.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque
from contextvars import ContextVar

TENANT_HEADER = "X-Pilosa-Tenant"
# THE canonical tenantless principal: every spelling of "no tenant"
# (missing header, empty string, whitespace, the legacy "-") lands
# here, so batcher admission, ledger rows and SLO accounting agree on
# one identity for untagged traffic (ISSUE 18 satellite).
DEFAULT_TENANT = "(default)"
_LEGACY_TENANTLESS = ("-",)

# Reserved site name for compile events no window or claim ever adopted
# (e.g. module-import-time warmers on threads that never dispatch).
UNATTRIBUTED = "(unattributed)"

# Principal tables are label sets headed for /metrics: bound cardinality.
_MAX_PRINCIPALS = 512
_OVERFLOW_PRINCIPAL = ("~overflow", "-", "-")
_MAX_TENANT_LEN = 64
_MAX_TRACKED = 8192  # per-site identity set cap (mirrors kernels._seen_programs)

# jax.monitoring event keys (jax 0.4.x).  backend_compile fires once per
# real XLA compile and never on a cache hit — it is the new-compile signal;
# the other two are folded into compile wall-time.
_EV_BACKEND = "/jax/core/compile/backend_compile_duration"
_EV_COMPILE_PREFIX = "/jax/core/compile/"

_tenant: ContextVar[str] = ContextVar("devledger_tenant", default=DEFAULT_TENANT)
# (index, op_class) bound by the api layer once both are known.
_binding: ContextVar[tuple] = ContextVar("devledger_binding", default=("-", "-"))
# Weighted principal list — set by the batcher around a shared flight so one
# launch is split across every tenant that rode it.
_weights: ContextVar[tuple] = ContextVar("devledger_weights", default=())


def active_window_site():
    """The site of this thread's innermost launch window, or None.  Lets
    shared funnels (``kernels.note_transfer``) book under the wrapping
    site — an ingest-upload window adopts the fragment sync's H2D bytes."""
    w = _tls.windows
    return w[-1].site if w else None


def clean_tenant(raw) -> str:
    """Sanitize a tenant label from the wire: printable, bounded,
    non-empty — and NORMALIZED: every tenantless spelling (None, "",
    whitespace, legacy "-") maps to the one canonical
    :data:`DEFAULT_TENANT` so per-tenant accounting never splits
    untagged traffic across aliases."""
    if not raw:
        return DEFAULT_TENANT
    t = "".join(c for c in str(raw).strip() if c.isprintable() and c not in '{}",\\')
    t = t[:_MAX_TENANT_LEN]
    if not t or t in _LEGACY_TENANTLESS:
        return DEFAULT_TENANT
    return t


def current_tenant() -> str:
    return _tenant.get()


def current_principal() -> tuple:
    idx, cls = _binding.get()
    return (_tenant.get(), idx, cls)


def ambient_weights() -> tuple:
    """The weighted principal list launches should book against:
    the batcher's flight-level split when set, else the single ambient
    principal at weight 1."""
    w = _weights.get()
    if w:
        return w
    return ((current_principal(), 1.0),)


@contextlib.contextmanager
def tenant_scope(tenant):
    tok = _tenant.set(clean_tenant(tenant))
    try:
        yield
    finally:
        _tenant.reset(tok)


@contextlib.contextmanager
def principal_scope(index="-", op_class="-"):
    tok = _binding.set((str(index or "-"), str(op_class or "-")))
    try:
        yield
    finally:
        _binding.reset(tok)


@contextlib.contextmanager
def weighted_scope(pairs):
    """``pairs`` is an iterable of ((tenant, index, op_class), weight); used
    by the batcher so one shared flight launch is attributed fractionally to
    every principal whose queries rode it."""
    tok = _weights.set(tuple(pairs))
    try:
        yield
    finally:
        _weights.reset(tok)


class _Accum:
    """One row of the cost table (a site, a principal, or the totals)."""

    __slots__ = (
        "compiles",
        "compile_ms",
        "launches",
        "launch_ms",
        "device_ms",
        "h2d_bytes",
        "d2h_bytes",
        "flops",
        "bytes_accessed",
        "cache_hits",
    )

    def __init__(self):
        self.compiles = 0
        self.compile_ms = 0.0
        self.launches = 0
        self.launch_ms = 0.0
        self.device_ms = 0.0
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.cache_hits = 0

    def to_dict(self, uptime=None):
        d = {
            "compiles": self.compiles,
            "compileMs": round(self.compile_ms, 3),
            "cacheHits": self.cache_hits,
            "launches": self.launches,
            "launchMs": round(self.launch_ms, 3),
            "deviceMs": round(self.device_ms, 3),
            "h2dBytes": self.h2d_bytes,
            "d2hBytes": self.d2h_bytes,
        }
        if self.flops or self.bytes_accessed:
            d["flops"] = self.flops
            d["bytesAccessed"] = self.bytes_accessed
        if uptime and uptime > 0:
            d["launchesPerSec"] = round(self.launches / uptime, 3)
            d["transferBytesPerSec"] = round(
                (self.h2d_bytes + self.d2h_bytes) / uptime, 1
            )
        return d


class _Window:
    """One active launch window on a thread's window stack.  The monitoring
    listener folds compile events into the innermost window; the window's
    exit books them against its site and the ambient principals."""

    __slots__ = ("site", "sig", "muted", "compiles", "compile_ms")

    def __init__(self, site, sig, muted=False):
        self.site = site
        self.sig = sig
        self.muted = muted
        self.compiles = 0
        self.compile_ms = 0.0


class _TLS(threading.local):
    def __init__(self):
        self.windows = []
        # compile events that fired with no window active on this thread,
        # waiting for the next Site.claim() (the kernels dispatch funnel
        # notes launches post-hoc); bounded so a non-dispatching thread
        # cannot grow it forever.
        self.stash_compiles = 0
        self.stash_ms = 0.0


_tls = _TLS()


class Site:
    """One registered launch site.  Cheap to hold; all mutation funnels
    through the owning ledger's lock except window bookkeeping, which is
    thread-local until the window exits."""

    __slots__ = ("name", "ledger", "acc", "_seen", "recent_sigs", "sig_ms")

    def __init__(self, name, ledger):
        self.name = name
        self.ledger = ledger
        self.acc = _Accum()
        self._seen = set()  # tracked callable/shape identities
        self.recent_sigs = deque(maxlen=8)
        # sig class (first token of the launch sig, e.g. "count" of
        # "count B8 S4") -> [launches, EWMA device-ms per launch]: the
        # measured price list the flight planner's lane chooser reads
        # instead of hardcoded warm-up heuristics (exec/planner.py)
        self.sig_ms: dict[str, list] = {}

    # -- identity tracking ------------------------------------------------
    def track(self, fn, key=()) -> bool:
        """Track a lowered/compiled callable identity (the function object
        plus a shape/static key).  Returns True the first time an identity
        is seen — the site-local compile-vs-cache-hit signal that backs the
        monitoring listener.  Records a cache hit otherwise."""
        return self.track_key((id(fn), key))

    def track_key(self, key) -> bool:
        """``track`` for callers that already hold a stable hashable
        identity (e.g. the kernels funnel's (kernel, lane, shape-sig))."""
        with self.ledger._lock:
            if key in self._seen:
                self.acc.cache_hits += 1
                return False
            if len(self._seen) < _MAX_TRACKED:
                self._seen.add(key)
        return True

    # -- direct recording -------------------------------------------------
    def record_compile(self, wall_s=0.0, sig=None, flops=None, bytes_accessed=None):
        self.ledger._book_compile(self, 1, wall_s * 1e3, sig)
        if flops or bytes_accessed:
            self.record_cost(flops or 0.0, bytes_accessed or 0.0)

    def record_launch(self, wall_s=0.0, n=1, device_s=None):
        self.ledger._book_launch(self, n, wall_s * 1e3, (device_s or wall_s) * 1e3)

    def record_transfer(self, nbytes, direction="h2d"):
        self.ledger._book_transfer(self, int(nbytes), direction)

    def record_cost(self, flops, bytes_accessed):
        with self.ledger._lock:
            self.acc.flops += float(flops)
            self.acc.bytes_accessed += float(bytes_accessed)

    # -- windows & claims -------------------------------------------------
    @contextlib.contextmanager
    def launch(self, sig=None, n=1, muted=False):
        """Wrap one device dispatch: measures launch wall time and adopts
        any XLA compile events that fire inside (same thread).  ``muted``
        windows swallow events without booking them — used around the
        opt-in cost_analysis AOT compile so it cannot double-count."""
        w = _Window(self, sig, muted=muted)
        _tls.windows.append(w)
        t0 = time.perf_counter()
        try:
            yield w
        finally:
            wall_ms = (time.perf_counter() - t0) * 1e3
            _tls.windows.pop()
            if not muted:
                if w.compiles:
                    self.ledger._book_compile(self, w.compiles, w.compile_ms, sig)
                # compile-carrying windows stay out of the per-sig price
                # list: the lane chooser wants the steady-state launch
                # cost, not the one-time trace+compile spike
                self.ledger._book_launch(
                    self, n, wall_ms, wall_ms,
                    sig=None if w.compiles else sig,
                )

    def claim(self, sig=None):
        """Adopt compile events this thread saw since the last claim —
        called by post-hoc dispatch funnels such as
        ``kernels._note_dispatch`` right after the jit call returns.
        Inside an enclosing window (a mesh dispatch wrapping kernel
        dispatches) the claim takes the window's pending events, so the
        most specific site wins; otherwise it drains the thread stash."""
        windows = _tls.windows
        if windows:
            w = windows[-1]
            n, ms = w.compiles, w.compile_ms
            if n or ms:
                w.compiles = 0
                w.compile_ms = 0.0
                if not w.muted:
                    self.ledger._book_compile(self, n, ms, sig)
            return 0 if w.muted else n
        n, ms = _tls.stash_compiles, _tls.stash_ms
        if n or ms:
            _tls.stash_compiles = 0
            _tls.stash_ms = 0.0
            self.ledger._book_compile(self, n, ms, sig)
        return n

    def snapshot(self, uptime=None):
        with self.ledger._lock:
            d = self.acc.to_dict(uptime)
            d["trackedIdentities"] = len(self._seen)
            d["recentCompileSigs"] = [s for s in self.recent_sigs]
        return d


class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self._sites = {}
        self._principals = {}
        self.totals = _Accum()
        self.unattributed = _Accum()
        self.started = time.monotonic()
        # storm detector
        self.storm_threshold = 8
        self.storm_window_s = 60.0
        self.warmup_s = 0.0
        self._warm_mark = False
        self._storm_events = deque()
        self._storm_cool_until = 0.0
        self.storms = deque(maxlen=8)
        self._storm_callbacks = []
        self._listener_installed = False

    # -- registration -----------------------------------------------------
    def site(self, name) -> Site:
        with self._lock:
            s = self._sites.get(name)
            if s is None:
                s = self._sites[name] = Site(name, self)
        self._ensure_listener()
        return s

    def on_storm(self, cb):
        """Register ``cb(bundle_dict)`` to run when a recompile storm
        trips.  Callbacks must not raise; failures are swallowed."""
        with self._lock:
            if cb not in self._storm_callbacks:
                self._storm_callbacks.append(cb)

    def configure_storm(self, threshold=None, window_s=None, warmup_s=None):
        with self._lock:
            if threshold is not None:
                self.storm_threshold = max(1, int(threshold))
            if window_s is not None:
                self.storm_window_s = float(window_s)
            if warmup_s is not None:
                self.warmup_s = float(warmup_s)

    def mark_warm(self):
        self._warm_mark = True

    def measured_ms(self, site_name, sig_class):
        """(launches, EWMA device-ms per launch) for one site's sig class,
        or None before any non-compiling launch booked there — the flight
        planner's lane chooser treats None as "no price yet, keep the
        heuristic" (exec/planner.py)."""
        with self._lock:
            s = self._sites.get(site_name)
            if s is None:
                return None
            row = s.sig_ms.get(str(sig_class))
            if row is None:
                return None
            return (row[0], row[1])

    @property
    def warm(self) -> bool:
        if self._warm_mark:
            return True
        return (time.monotonic() - self.started) >= self.warmup_s > 0

    def reset(self):
        """Zero every table and re-arm the storm detector (tests/benches).
        Registered sites and callbacks survive; the listener stays."""
        with self._lock:
            for s in self._sites.values():
                s.acc = _Accum()
                s._seen.clear()
                s.recent_sigs.clear()
                s.sig_ms.clear()
            self._principals.clear()
            self.totals = _Accum()
            self.unattributed = _Accum()
            self.started = time.monotonic()
            self._warm_mark = False
            self._storm_events.clear()
            self._storm_cool_until = 0.0
            self.storms.clear()
        _tls.stash_compiles = 0
        _tls.stash_ms = 0.0

    # -- principal table --------------------------------------------------
    def _principal_row(self, principal) -> _Accum:
        # caller holds self._lock
        row = self._principals.get(principal)
        if row is None:
            if len(self._principals) >= _MAX_PRINCIPALS:
                principal = _OVERFLOW_PRINCIPAL
                row = self._principals.get(principal)
                if row is None:
                    row = self._principals[principal] = _Accum()
            else:
                row = self._principals[principal] = _Accum()
        return row

    # -- booking ----------------------------------------------------------
    def _book_compile(self, site, n, ms, sig):
        weights = ambient_weights()
        with self._lock:
            site.acc.compiles += n
            site.acc.compile_ms += ms
            if sig is not None:
                site.recent_sigs.append(str(sig))
            self.totals.compiles += n
            self.totals.compile_ms += ms
            for principal, w in weights:
                row = self._principal_row(principal)
                row.compiles += n  # compiles are indivisible; book whole
                row.compile_ms += ms * w
        self._note_storm(site.name, sig, n)

    # per-site sig-class price rows kept (first-come; real sig vocabularies
    # are a handful of op classes) and the EWMA smoothing factor
    _MAX_SIG_CLASSES = 32
    _SIG_EWMA_ALPHA = 0.25

    def _book_launch(self, site, n, wall_ms, device_ms, sig=None):
        weights = ambient_weights()
        with self._lock:
            site.acc.launches += n
            site.acc.launch_ms += wall_ms
            site.acc.device_ms += device_ms
            if sig is not None:
                cls = str(sig).split(None, 1)[0]
                row = site.sig_ms.get(cls)
                per = device_ms / max(n, 1)
                if row is not None:
                    row[0] += n
                    row[1] += self._SIG_EWMA_ALPHA * (per - row[1])
                elif len(site.sig_ms) < self._MAX_SIG_CLASSES:
                    site.sig_ms[cls] = [n, per]
            self.totals.launches += n
            self.totals.launch_ms += wall_ms
            self.totals.device_ms += device_ms
            for principal, w in weights:
                row = self._principal_row(principal)
                row.launches += max(1, round(n * w)) if n else 0
                row.launch_ms += wall_ms * w
                row.device_ms += device_ms * w

    def _book_transfer(self, site, nbytes, direction):
        weights = ambient_weights()
        with self._lock:
            if direction == "d2h":
                site.acc.d2h_bytes += nbytes
                self.totals.d2h_bytes += nbytes
            else:
                site.acc.h2d_bytes += nbytes
                self.totals.h2d_bytes += nbytes
            for principal, w in weights:
                row = self._principal_row(principal)
                if direction == "d2h":
                    row.d2h_bytes += int(nbytes * w)
                else:
                    row.h2d_bytes += int(nbytes * w)

    def _book_unattributed(self, n, ms):
        with self._lock:
            self.unattributed.compiles += n
            self.unattributed.compile_ms += ms
            self.totals.compiles += n
            self.totals.compile_ms += ms
        self._note_storm(UNATTRIBUTED, None, n)

    # -- storm detector ---------------------------------------------------
    def _note_storm(self, site_name, sig, n=1):
        if not n or not self.warm:
            return
        now = time.monotonic()
        bundle = None
        with self._lock:
            for _ in range(n):
                self._storm_events.append((now, site_name, sig))
            horizon = now - self.storm_window_s
            while self._storm_events and self._storm_events[0][0] < horizon:
                self._storm_events.popleft()
            if (
                len(self._storm_events) >= self.storm_threshold
                and now >= self._storm_cool_until
            ):
                by_site = {}
                shapes = []
                for _, s, g in self._storm_events:
                    by_site[s] = by_site.get(s, 0) + 1
                    if g is not None:
                        shapes.append(str(g))
                bundle = {
                    "type": "recompile-storm",
                    "atUnix": time.time(),
                    "count": len(self._storm_events),
                    "threshold": self.storm_threshold,
                    "windowSec": self.storm_window_s,
                    "sites": dict(
                        sorted(by_site.items(), key=lambda kv: -kv[1])
                    ),
                    "shapes": shapes[-16:],
                }
                self.storms.append(bundle)
                # re-arm only after a quiet window so one storm emits one
                # incident, not one per compile past the threshold
                self._storm_cool_until = now + self.storm_window_s
                cbs = list(self._storm_callbacks)
        if bundle is not None:
            for cb in cbs:
                try:
                    cb(bundle)
                except Exception:  # graftlint: disable=exception-hygiene -- storm callbacks are best-effort; a broken sink must not break accounting
                    pass

    # -- jax.monitoring bridge --------------------------------------------
    def _ensure_listener(self):
        if self._listener_installed:
            return
        with self._lock:
            if self._listener_installed:
                return
            self._listener_installed = True
        try:
            from jax import monitoring as _mon

            _mon.register_event_duration_secs_listener(self._on_event)
        except Exception:
            # no jax / no monitoring API: sites still work via explicit
            # record_compile / track(); only automatic detection is lost
            self._listener_installed = True

    def _on_event(self, key, seconds, **kw):
        """jax.monitoring duration listener.  Fires synchronously in the
        compiling thread, so the thread's window stack and the request
        contextvars are the right attribution context.  Must never raise."""
        try:
            if not key.startswith(_EV_COMPILE_PREFIX):
                return
            ms = seconds * 1e3
            is_compile = key == _EV_BACKEND
            windows = _tls.windows
            if windows:
                w = windows[-1]
                if w.muted:
                    return
                if is_compile:
                    w.compiles += 1
                w.compile_ms += ms
                site_name = w.site.name
                sig = w.sig
            else:
                if is_compile:
                    _tls.stash_compiles += 1
                _tls.stash_ms += ms
                site_name = None
                sig = None
                if is_compile and _tls.stash_compiles > 64:
                    # stranded stash: fold into the reserved bucket so the
                    # totals stay honest even on never-dispatching threads
                    n, tot = _tls.stash_compiles, _tls.stash_ms
                    _tls.stash_compiles = 0
                    _tls.stash_ms = 0.0
                    self._book_unattributed(n, tot)
            if is_compile:
                self._annotate_span(site_name, sig, ms)
        except Exception:  # graftlint: disable=exception-hygiene -- a listener raise would propagate into XLA's compile path
            pass

    @staticmethod
    def _annotate_span(site_name, sig, ms):
        try:
            from pilosa_tpu.obs import tracing

            sp = tracing.active_span()
            if sp is not None:
                sp.log_kv(
                    event="xla_compile",
                    site=site_name or UNATTRIBUTED,
                    sig=str(sig) if sig is not None else "-",
                    compileMs=round(ms, 3),
                )
                sp.set_tag("xlaCompiles", int(sp.tags.get("xlaCompiles", 0)) + 1)
        except Exception:  # graftlint: disable=exception-hygiene -- span annotation is advisory; tracing must never fail a compile
            pass

    # -- opt-in AOT cost analysis -----------------------------------------
    def analyze_cost(self, site, fn, *args, sig=None, **kwargs):
        """Best-effort ``cost_analysis()`` FLOPs/bytes for ``fn(*args)``.
        On this backend ``fn.lower().compile()`` does NOT share the jit call
        cache, so this pays a duplicate compile — gated behind
        PILOSA_DEVCOST_ANALYSIS=1 and run inside a muted window so the
        duplicate never pollutes compile counts or the storm detector."""
        if os.environ.get("PILOSA_DEVCOST_ANALYSIS", "") != "1":
            return None
        try:
            with site.launch(sig=sig, muted=True):
                compiled = fn.lower(*args, **kwargs).compile()
            costs = compiled.cost_analysis()
            if isinstance(costs, (list, tuple)):
                costs = costs[0] if costs else {}
            flops = float(costs.get("flops", 0.0))
            nbytes = float(costs.get("bytes accessed", 0.0))
            site.record_cost(flops, nbytes)
            return {"flops": flops, "bytesAccessed": nbytes}
        except Exception:
            return None

    # -- exposition -------------------------------------------------------
    def counters(self) -> dict:
        """Flat counter map for cheap before/after deltas (bench, loadgen,
        flight recorder segments)."""
        with self._lock:
            out = {
                "compiles": self.totals.compiles,
                "compileMs": round(self.totals.compile_ms, 3),
                "launches": self.totals.launches,
                "deviceMs": round(self.totals.device_ms, 3),
                "h2dBytes": self.totals.h2d_bytes,
                "d2hBytes": self.totals.d2h_bytes,
                "storms": len(self.storms),
            }
            for name, s in self._sites.items():
                out[f"site.{name}.compiles"] = s.acc.compiles
                out[f"site.{name}.launches"] = s.acc.launches
                out[f"site.{name}.transferBytes"] = (
                    s.acc.h2d_bytes + s.acc.d2h_bytes
                )
        return out

    def tenant_totals(self) -> dict:
        """Per-TENANT aggregation over the principal table — the QoS
        governor's debt read-side (server/qos.py debits weighted-fair
        queues by these measured device-ms, not by query counts)."""
        with self._lock:
            out: dict = {}
            for (tenant, _idx, _cls), row in self._principals.items():
                t = out.get(tenant)
                if t is None:
                    t = out[tenant] = {
                        "deviceMs": 0.0,
                        "compileMs": 0.0,
                        "launches": 0,
                        "transferBytes": 0,
                    }
                t["deviceMs"] += row.device_ms
                t["compileMs"] += row.compile_ms
                t["launches"] += row.launches
                t["transferBytes"] += row.h2d_bytes + row.d2h_bytes
        for t in out.values():
            t["deviceMs"] = round(t["deviceMs"], 3)
            t["compileMs"] = round(t["compileMs"], 3)
        return out

    def snapshot(self) -> dict:
        uptime = max(time.monotonic() - self.started, 1e-9)
        with self._lock:
            sites = {}
            for name, s in sorted(self._sites.items()):
                d = s.acc.to_dict(uptime)
                d["trackedIdentities"] = len(s._seen)
                if s.recent_sigs:
                    d["recentCompileSigs"] = list(s.recent_sigs)
                if s.sig_ms:
                    d["measuredMs"] = {
                        cls: {"launches": row[0], "ewmaMs": round(row[1], 4)}
                        for cls, row in sorted(s.sig_ms.items())
                    }
                sites[name] = d
            principals = []
            for (tenant, idx, cls), row in sorted(self._principals.items()):
                p = row.to_dict(uptime)
                p["tenant"] = tenant
                p["index"] = idx
                p["opClass"] = cls
                principals.append(p)
            snap = {
                "uptimeSec": round(uptime, 3),
                "warm": self.warm,
                "totals": self.totals.to_dict(uptime),
                "unattributed": {
                    "compiles": self.unattributed.compiles,
                    "compileMs": round(self.unattributed.compile_ms, 3),
                },
                "sites": sites,
                "principals": principals,
                "storm": {
                    "threshold": self.storm_threshold,
                    "windowSec": self.storm_window_s,
                    "warmupSec": self.warmup_s,
                    "recent": list(self.storms),
                },
            }
        return snap

    def prometheus_text(self) -> str:
        out = []

        def emit(metric, help_text, rows):
            out.append(f"# HELP pilosa_{metric} {help_text}")
            out.append(f"# TYPE pilosa_{metric} counter")
            for labels, value in rows:
                lbl = ",".join(f'{k}="{v}"' for k, v in labels)
                out.append(f"pilosa_{metric}{{{lbl}}} {value}")

        with self._lock:
            site_rows = [(n, s.acc) for n, s in sorted(self._sites.items())]
            prin_rows = sorted(self._principals.items())
            unat = self.unattributed.compiles
        emit(
            "dev_compiles",
            "XLA compiles per ledger site",
            [((("site", n),), a.compiles) for n, a in site_rows]
            + [((("site", UNATTRIBUTED),), unat)],
        )
        emit(
            "dev_compile_ms",
            "XLA compile wall milliseconds per ledger site",
            [((("site", n),), round(a.compile_ms, 3)) for n, a in site_rows],
        )
        emit(
            "dev_launches",
            "device launches per ledger site",
            [((("site", n),), a.launches) for n, a in site_rows],
        )
        emit(
            "dev_device_ms",
            "device launch milliseconds per ledger site",
            [((("site", n),), round(a.device_ms, 3)) for n, a in site_rows],
        )
        emit(
            "dev_transfer_bytes",
            "host<->device bytes per ledger site",
            [
                ((("site", n), ("direction", "h2d")), a.h2d_bytes)
                for n, a in site_rows
            ]
            + [
                ((("site", n), ("direction", "d2h")), a.d2h_bytes)
                for n, a in site_rows
            ],
        )
        emit(
            "dev_tenant_launches",
            "device launches per principal",
            [
                (
                    (("tenant", t), ("index", i), ("op_class", c)),
                    a.launches,
                )
                for (t, i, c), a in prin_rows
            ],
        )
        emit(
            "dev_tenant_device_ms",
            "device milliseconds per principal",
            [
                (
                    (("tenant", t), ("index", i), ("op_class", c)),
                    round(a.device_ms, 3),
                )
                for (t, i, c), a in prin_rows
            ],
        )
        emit(
            "dev_tenant_transfer_bytes",
            "host<->device bytes per principal",
            [
                (
                    (("tenant", t), ("index", i), ("op_class", c)),
                    a.h2d_bytes + a.d2h_bytes,
                )
                for (t, i, c), a in prin_rows
            ],
        )
        emit("dev_storms", "recompile storm incidents", [((("kind", "recompile"),), len(self.storms))])
        return "\n".join(out) + "\n"


_LEDGER = Ledger()


def ledger() -> Ledger:
    return _LEDGER


def site(name) -> Site:
    return _LEDGER.site(name)


def snapshot() -> dict:
    return _LEDGER.snapshot()


def counters() -> dict:
    return _LEDGER.counters()


def tenant_totals() -> dict:
    return _LEDGER.tenant_totals()


def prometheus_text() -> str:
    return _LEDGER.prometheus_text()


def reset() -> None:
    _LEDGER.reset()


def measured_ms(site_name, sig_class):
    return _LEDGER.measured_ms(site_name, sig_class)


def mark_warm() -> None:
    _LEDGER.mark_warm()


def configure_storm(threshold=None, window_s=None, warmup_s=None) -> None:
    _LEDGER.configure_storm(threshold, window_s, warmup_s)


def on_storm(cb) -> None:
    _LEDGER.on_storm(cb)
