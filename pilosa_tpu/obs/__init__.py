"""Observability: stats, tracing, diagnostics (reference: stats/,
tracing/, prometheus/, statsd/, diagnostics.go, gopsutil/, gcnotify/)."""
