"""Diagnostics collector (reference: diagnostics.go:41-120 + server.go
:740-790 monitorDiagnostics).

The reference phones home a JSON snapshot (version, cluster shape,
schema scale, host info) on an interval. This build has no egress, so
the collector exposes the same snapshot locally — served at
``/internal/diagnostics`` and optionally appended to a JSONL file sink
for offline collection — with the same field vocabulary so downstream
tooling ports over.
"""

from __future__ import annotations

import json
import threading
import time

from pilosa_tpu.obs.sysinfo import SystemInfo


def _pallas_fallback_count() -> int:
    try:
        from pilosa_tpu.ops.kernels import pallas_fallback_count

        return pallas_fallback_count()
    except Exception:
        return 0


class Diagnostics:
    def __init__(self, holder, cluster=None, version: str = "", sink_path: str | None = None):
        self.holder = holder
        self.cluster = cluster
        self.version = version
        self.sink_path = sink_path
        self.start_time = time.time()
        self.info = SystemInfo()
        self._lock = threading.Lock()
        self._extra: dict = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.flush_errors = 0

    def set(self, key: str, value) -> None:
        """reference diagnostics.Set — arbitrary reported fields."""
        with self._lock:
            self._extra[key] = value

    def snapshot(self) -> dict:
        """One report (reference CheckVersion/logErr payload fields:
        Version, NumNodes, NumIndexes/Fields/Views, OS info...)."""
        num_fields = num_views = num_fragments = 0
        shards: set[int] = set()
        for name in self.holder.index_names():
            idx = self.holder.index(name)
            if idx is None:
                continue
            for fname in idx.field_names(include_internal=True):
                field = idx.field(fname)
                if field is None:
                    continue
                num_fields += 1
                for vname in field.view_names():
                    view = field.view(vname)
                    num_views += 1
                    num_fragments += len(view.fragments)
                    shards |= set(view.fragments)
        report = {
            "version": self.version,
            "uptime": int(time.time() - self.start_time),
            "numNodes": len(self.cluster.nodes) if self.cluster is not None else 1,
            "numIndexes": len(self.holder.index_names()),
            "numFields": num_fields,
            "numViews": num_views,
            "numFragments": num_fragments,
            "numShards": len(shards),
            "system": self.info.to_dict(),
            # Silent Pallas→XLA kernel demotions after the backend was
            # proven good — repeated failures signal device OOM or a
            # miscompiled shape (kernels._note_pallas_fallback).
            "pallasFallbacks": _pallas_fallback_count(),
        }
        with self._lock:
            report.update(self._extra)
        return report

    def flush(self) -> dict:
        """Emit one report to the sink (reference diagnostics.Flush)."""
        report = self.snapshot()
        if self.sink_path:
            try:
                with open(self.sink_path, "a") as f:
                    f.write(json.dumps(report) + "\n")
            except OSError:
                pass
        return report

    # -- interval loop (reference server.go:740-790) ------------------------

    def start(self, interval: float) -> None:
        def run():
            while not self._stop.wait(interval):
                try:
                    self.flush()
                except Exception:
                    # the reporter loop must survive a bad flush; the
                    # counter keeps the failure visible in the report
                    self.flush_errors += 1

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
