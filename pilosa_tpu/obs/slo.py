"""SLO plane: per-op-class latency quantiles and availability error
budgets at the HTTP/API boundary.

Every request is classified into an op class — read queries by their
top-level PQL call (``read.count``/``read.topn``/``read.row``/
``read.range``/``read.groupby``/``read.other``), ``write`` for any
query carrying a write call, ``import`` for the bulk paths,
``translate`` for key translation, ``internal`` for node↔node fan-out
sub-requests, ``other`` for everything else.  Per class the tracker
maintains:

* sliding-window latency quantiles (p50/p99/p999) over log-linear
  sub-ms buckets (10 µs floor — finer than obs/stats.py's histogram,
  which is what makes a 0.07-0.16 ms/op serving floor resolvable);
* availability over the multi-window multi-burn-rate scheme of the
  Google SRE Workbook (ch. 5): a "fast" page rule (1 h long / 5 m
  short windows at 14.4× budget burn) and a "slow" ticket rule
  (3 d / 6 h at 1×).  A rule fires only when BOTH its windows burn
  above the factor — the short window makes the alert reset quickly,
  the long window makes it ignore blips.

Errors are server-attributed failures: 5xx responses and deadline
504s — which is how batcher queue expiries and bypass timeouts
(server/batcher.py) land on the budget.  4xx client mistakes do not
burn budget.

Exposition: ``/debug/slo`` (full snapshot), ``pilosa_slo_*`` series in
``/metrics`` (rendered by :meth:`SLOTracker.prometheus_text`), and an
``slo`` block in ``/debug/vars``.

The op class crosses the API→HTTP layer boundary through a
contextvar (:func:`note_class`/:func:`take_class`): the API layer has
the parsed query, the HTTP layer has the response outcome and the
clock.  ThreadingHTTPServer runs one thread per connection and each
thread has its own context, so a class noted during dispatch is read
back by the same request's ``finally``.
"""

from __future__ import annotations

import contextvars
import math
import threading
import time

from pilosa_tpu.obs import devledger

# -- op classes ---------------------------------------------------------

OP_READ_COUNT = "read.count"
OP_READ_TOPN = "read.topn"
OP_READ_ROW = "read.row"
OP_READ_RANGE = "read.range"
OP_READ_GROUPBY = "read.groupby"
OP_READ_OTHER = "read.other"
OP_WRITE = "write"
OP_IMPORT = "import"
OP_TRANSLATE = "translate"
OP_INTERNAL = "internal"
OP_OTHER = "other"

_READ_CLASS_BY_CALL = {
    "Count": OP_READ_COUNT,
    "TopN": OP_READ_TOPN,
    "Row": OP_READ_ROW,
    "Range": OP_READ_RANGE,
    "GroupBy": OP_READ_GROUPBY,
}


def classify_query(query) -> str:
    """Op class of a parsed PQL query: any write call makes the whole
    request a write (strict in-order semantics mean the write dominates
    the request's fate); otherwise the FIRST top-level call names the
    read class."""
    if query.write_calls():
        return OP_WRITE
    calls = getattr(query, "calls", ())
    if calls:
        return _READ_CLASS_BY_CALL.get(calls[0].name, OP_READ_OTHER)
    return OP_READ_OTHER


# The API layer notes the class mid-dispatch; the HTTP layer's finally
# takes (and clears) it.  Default None = fall back to the route class.
_op_class: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "slo_op_class", default=None
)


def note_class(op_class: str) -> None:
    _op_class.set(op_class)


def take_class() -> str | None:
    c = _op_class.get()
    if c is not None:
        _op_class.set(None)
    return c


# -- per-tenant dimension ------------------------------------------------

# A tenant-scoped objective class is spelled "op_class@tenant": the
# tracker records a tenant's request under BOTH the base class and the
# tenant class, so global burn math is undisturbed while a tenant can
# carry its own objective/error budget (the QoS governor's per-victim
# signal, server/qos.py).
_TENANT_SEP = "@"

# Distinct non-default tenants auto-tracked without an explicit
# objective; bounds the /metrics class cardinality.
_MAX_TRACKED_TENANTS = 32


def tenant_class(op_class: str, tenant: str) -> str:
    return f"{op_class}{_TENANT_SEP}{tenant}"


# -- latency buckets ----------------------------------------------------

# Log-linear bounds (1/2.5/5 per decade), 10 µs .. 60 s.  Finer at the
# bottom than obs/stats.py HISTOGRAM_BUCKETS: quantile interpolation
# needs resolution below the serving floor, not just a bucket edge at it.
LATENCY_BOUNDS: tuple[float, ...] = tuple(
    round(m * 10.0**e, 10)
    for e in range(-5, 2)
    for m in (1.0, 2.5, 5.0)
) + (60.0,)
_N_BUCKETS = len(LATENCY_BOUNDS) + 1  # + overflow


class Objective:
    """One class's targets: availability (success ratio) and optionally
    a p99 latency bound in seconds."""

    __slots__ = ("availability", "latency_p99")

    def __init__(self, availability: float, latency_p99: float | None = None):
        if not (0.0 < availability < 1.0):
            raise ValueError("availability target must be in (0, 1)")
        self.availability = availability
        self.latency_p99 = latency_p99

    def to_dict(self) -> dict:
        return {
            "availability": self.availability,
            "latencyP99Ms": (
                self.latency_p99 * 1e3 if self.latency_p99 is not None else None
            ),
        }


class BurnRule:
    """One multi-window alert rule: fires when budget burn exceeds
    ``factor``× in BOTH the long and short windows (SRE Workbook ch. 5
    "multiwindow, multi-burn-rate alerts")."""

    __slots__ = ("name", "long", "short", "factor")

    def __init__(self, name: str, long: float, short: float, factor: float):
        self.name = name
        self.long = float(long)
        self.short = float(short)
        self.factor = float(factor)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "longWindow": _window_name(self.long),
            "shortWindow": _window_name(self.short),
            "factor": self.factor,
        }


DEFAULT_BURN_RULES: tuple[BurnRule, ...] = (
    BurnRule("fast", long=3600.0, short=300.0, factor=14.4),
    BurnRule("slow", long=259200.0, short=21600.0, factor=1.0),
)

# Objectives by class; classes absent here (other/internal) are tracked
# for volume/latency but carry no objective and never fail a verdict.
DEFAULT_OBJECTIVES: dict[str, Objective] = {
    OP_READ_COUNT: Objective(0.999, 0.050),
    OP_READ_TOPN: Objective(0.999, 0.100),
    OP_READ_ROW: Objective(0.999, 0.050),
    OP_READ_RANGE: Objective(0.999, 0.100),
    OP_READ_GROUPBY: Objective(0.99, 0.250),
    OP_READ_OTHER: Objective(0.99, 0.250),
    OP_WRITE: Objective(0.999, 0.050),
    OP_IMPORT: Objective(0.99, 1.0),
    OP_TRANSLATE: Objective(0.999, 0.050),
}


def _window_name(seconds: float) -> str:
    s = int(round(seconds))
    if s % 86400 == 0:
        return f"{s // 86400}d"
    if s % 3600 == 0:
        return f"{s // 3600}h"
    if s % 60 == 0:
        return f"{s // 60}m"
    return f"{s}s"


def _bucket_of(v: float) -> int:
    # LATENCY_BOUNDS is tiny (~22); linear scan beats bisect's call
    # overhead at this size and is branch-predictable for fast requests.
    for i, bound in enumerate(LATENCY_BOUNDS):
        if v <= bound:
            return i
    return _N_BUCKETS - 1


class _Ring:
    """Fixed ring of time slots covering ``window`` seconds; each slot
    is [abs_slot_idx, total, errors, bucket_counts].  A slot is lazily
    reset the first time an observation lands in a new time slice, so
    idle periods cost nothing."""

    __slots__ = ("slot_seconds", "slots")

    def __init__(self, window: float, slot_seconds: float):
        n = max(2, int(math.ceil(window / slot_seconds)) + 1)
        self.slot_seconds = slot_seconds
        self.slots: list[list] = [
            [-1, 0, 0, None] for _ in range(n)
        ]

    def observe(self, now: float, error: bool, bucket: int | None) -> None:
        idx = int(now / self.slot_seconds)
        slot = self.slots[idx % len(self.slots)]
        if slot[0] != idx:
            slot[0] = idx
            slot[1] = 0
            slot[2] = 0
            slot[3] = None
        slot[1] += 1
        if error:
            slot[2] += 1
        if bucket is not None:
            counts = slot[3]
            if counts is None:
                counts = slot[3] = [0] * _N_BUCKETS
            counts[bucket] += 1

    def sum_window(self, now: float, window: float) -> tuple[int, int]:
        """(total, errors) over the trailing ``window`` seconds."""
        lo = int((now - window) / self.slot_seconds) + 1
        hi = int(now / self.slot_seconds)
        total = errors = 0
        slots = self.slots
        n = len(slots)
        if hi - lo + 1 < n:
            # walk only the slot indices the window can cover — a
            # short window over a long-lived ring (e.g. the 5m burn
            # window over the 3d ring) is a tiny fraction of it
            for idx in range(lo, hi + 1):
                slot = slots[idx % n]
                if slot[0] == idx:
                    total += slot[1]
                    errors += slot[2]
        else:
            for slot in slots:
                if lo <= slot[0] <= hi:
                    total += slot[1]
                    errors += slot[2]
        return total, errors

    def merged_buckets(self, now: float, window: float) -> list[int]:
        lo = int((now - window) / self.slot_seconds) + 1
        hi = int(now / self.slot_seconds)
        out = [0] * _N_BUCKETS
        slots = self.slots
        n = len(slots)
        if hi - lo + 1 < n:
            candidates = [
                slot
                for idx in range(lo, hi + 1)
                for slot in (slots[idx % n],)
                if slot[0] == idx and slot[3] is not None
            ]
        else:
            candidates = [
                s for s in slots if lo <= s[0] <= hi and s[3] is not None
            ]
        for slot in candidates:
            counts = slot[3]
            for i in range(_N_BUCKETS):
                out[i] += counts[i]
        return out


def _quantile(buckets: list[int], q: float) -> float | None:
    """Interpolated quantile from per-bucket counts (not cumulative).
    Overflow observations report the top bound — a floor, stated as
    such in the snapshot (``p* >= 60s`` is still actionable)."""
    total = sum(buckets)
    if total == 0:
        return None
    rank = q * total
    cum = 0.0
    for i, c in enumerate(buckets):
        if c == 0:
            continue
        if cum + c >= rank:
            if i >= len(LATENCY_BOUNDS):
                return LATENCY_BOUNDS[-1]
            lo = LATENCY_BOUNDS[i - 1] if i > 0 else 0.0
            hi = LATENCY_BOUNDS[i]
            frac = (rank - cum) / c
            return lo + (hi - lo) * frac
        cum += c
    return LATENCY_BOUNDS[-1]


class _ClassState:
    __slots__ = ("total", "errors", "ring", "lat_buckets", "lat_sum",
                 "exemplars")

    def __init__(self, slot_seconds: float, max_window: float):
        self.total = 0
        self.errors = 0
        self.ring = _Ring(max_window, slot_seconds)
        # lifetime (non-windowed) duration histogram for the Prometheus
        # exposition — monotone, so scrapers can rate() it; the windowed
        # ring stays the quantile source.  Per-bucket counts, cumulated
        # at render time.
        self.lat_buckets = [0] * _N_BUCKETS
        self.lat_sum = 0.0
        # per-bucket (trace_id_hex, seconds, unix_ts): most recent trace
        # the tail sampler KEPT that landed in this bucket
        self.exemplars: list[tuple[str, float, float] | None] = (
            [None] * _N_BUCKETS
        )


class SLOTracker:
    """Thread-safe SLO accounting, one instance per Holder (wired like
    the event journal / job tracker).

    ``slot_seconds`` trades ring memory for window edge accuracy; the
    default 5 s keeps the 3 d ring at ~52k slots of four small fields
    per active class.  Tests shrink windows via ``burn_rules`` and
    ``latency_window`` so burn behavior is observable in milliseconds.
    """

    def __init__(
        self,
        objectives: dict[str, Objective] | None = None,
        burn_rules: tuple[BurnRule, ...] | None = None,
        slot_seconds: float = 5.0,
        latency_window: float = 300.0,
        budget_period: float = 30 * 86400.0,
    ):
        self.objectives = dict(
            DEFAULT_OBJECTIVES if objectives is None else objectives
        )
        self.burn_rules = tuple(
            DEFAULT_BURN_RULES if burn_rules is None else burn_rules
        )
        self.slot_seconds = float(slot_seconds)
        self.latency_window = float(latency_window)
        self.budget_period = float(budget_period)
        windows = {r.long for r in self.burn_rules} | {
            r.short for r in self.burn_rules
        }
        self._windows = tuple(sorted(windows))
        self._max_window = max(
            max(windows, default=latency_window), latency_window
        )
        self._lock = threading.Lock()
        self._classes: dict[str, _ClassState] = {}
        self._tenants_seen: set[str] = set()
        self.started = time.monotonic()

    # -- recording -----------------------------------------------------

    def observe(
        self,
        op_class: str,
        seconds: float,
        error: bool = False,
        tenant: str | None = None,
    ) -> None:
        """Record one request.  With ``tenant`` set, the request also
        lands under the tenant-scoped class ``op_class@tenant`` —
        always when that class carries an objective, and for up to
        ``_MAX_TRACKED_TENANTS`` distinct non-default tenants besides
        (cardinality stays bounded; the default tenant's traffic IS
        the base class, so it gets no duplicate row)."""
        bucket = _bucket_of(seconds)
        now = time.monotonic()
        with self._lock:
            keys = [op_class]
            if tenant:
                tkey = tenant_class(op_class, tenant)
                track = tkey in self.objectives
                if not track and tenant != devledger.DEFAULT_TENANT:
                    if tenant in self._tenants_seen:
                        track = True
                    elif len(self._tenants_seen) < _MAX_TRACKED_TENANTS:
                        self._tenants_seen.add(tenant)
                        track = True
                if track:
                    keys.append(tkey)
            for key in keys:
                st = self._classes.get(key)
                if st is None:
                    st = self._classes[key] = _ClassState(
                        self.slot_seconds, self._max_window
                    )
                st.total += 1
                if error:
                    st.errors += 1
                st.ring.observe(now, error, bucket)
                st.lat_buckets[bucket] += 1
                st.lat_sum += seconds

    def attach_exemplar(
        self, op_class: str, seconds: float, trace_id: str
    ) -> None:
        """Record a tail-KEPT trace as the exemplar for its latency
        bucket (wired from TraceStore.on_keep): /metrics bucket lines
        then point at a trace /debug/traces can actually serve."""
        bucket = _bucket_of(seconds)
        with self._lock:
            st = self._classes.get(op_class)
            if st is None:
                st = self._classes[op_class] = _ClassState(
                    self.slot_seconds, self._max_window
                )
            st.exemplars[bucket] = (trace_id, seconds, time.time())

    # -- exposition ----------------------------------------------------

    def _class_names(self) -> list[str]:
        names = set(self.objectives) | set(self._classes)
        return sorted(names)

    def series_sample(self) -> dict:
        """Cheap per-tick sample for the metrics-history ring
        (obs/history.py): active classes only, the latency window
        only.

        ``snapshot()`` walks every objective class across every burn
        window — exposition-grade work, wrong for a ~1 s sampler
        cadence.  This touches only classes that have observed traffic
        and only short-window slots, so its cost tracks live
        cardinality, not objective/burn-rule configuration."""
        now = time.monotonic()
        out: dict[str, dict] = {}
        with self._lock:
            for name, st in self._classes.items():
                obj = self.objectives.get(name)
                total, errors = st.ring.sum_window(
                    now, self.latency_window
                )
                merged = st.ring.merged_buckets(now, self.latency_window)
                p50 = _quantile(merged, 0.50)
                p99 = _quantile(merged, 0.99)
                ratio = errors / total if total else 0.0
                d = {
                    # lifetime counters: the sampler turns these into
                    # per-second rates by differencing ticks
                    "total": st.total,
                    "errors": st.errors,
                    "availability": 1.0 - ratio,
                    "p50Ms": p50 * 1e3 if p50 is not None else None,
                    "p99Ms": p99 * 1e3 if p99 is not None else None,
                }
                if obj is not None:
                    d["burnRate"] = ratio / (1.0 - obj.availability)
                out[name] = d
        return out

    def snapshot(self) -> dict:
        """Full live objective state — the /debug/slo payload."""
        now = time.monotonic()
        out_classes: dict[str, dict] = {}
        with self._lock:
            names = self._class_names()
            for name in names:
                st = self._classes.get(name)
                obj = self.objectives.get(name)
                budget = 1.0 - obj.availability if obj is not None else None
                win_out: dict[str, dict] = {}
                for w in self._windows:
                    total, errors = (
                        st.ring.sum_window(now, w) if st is not None else (0, 0)
                    )
                    ratio = errors / total if total else 0.0
                    d = {
                        "total": total,
                        "errors": errors,
                        "errorRatio": ratio,
                        "availability": 1.0 - ratio,
                    }
                    if budget:
                        burn = ratio / budget
                        d["burnRate"] = burn
                        # fraction of the budget_period error budget this
                        # window's burn consumes, were it sustained only
                        # for the window (SRE Workbook's accounting)
                        d["budgetConsumed"] = burn * (w / self.budget_period)
                    win_out[_window_name(w)] = d
                alerts = {}
                for rule in self.burn_rules:
                    lt, le = (
                        st.ring.sum_window(now, rule.long)
                        if st is not None
                        else (0, 0)
                    )
                    sht, she = (
                        st.ring.sum_window(now, rule.short)
                        if st is not None
                        else (0, 0)
                    )
                    firing = False
                    if budget and lt and sht:
                        firing = (
                            (le / lt) / budget >= rule.factor
                            and (she / sht) / budget >= rule.factor
                        )
                    alerts[rule.name] = firing
                merged = (
                    st.ring.merged_buckets(now, self.latency_window)
                    if st is not None
                    else [0] * _N_BUCKETS
                )
                lat_count = sum(merged)
                p50 = _quantile(merged, 0.50)
                p99 = _quantile(merged, 0.99)
                p999 = _quantile(merged, 0.999)
                latency_ok = None
                if obj is not None and obj.latency_p99 is not None and p99 is not None:
                    latency_ok = p99 <= obj.latency_p99
                ok = None
                if obj is not None:
                    ok = not any(alerts.values()) and latency_ok is not False
                out_classes[name] = {
                    "objective": obj.to_dict() if obj is not None else None,
                    "total": st.total if st is not None else 0,
                    "errors": st.errors if st is not None else 0,
                    "windows": win_out,
                    "latency": {
                        "window": _window_name(self.latency_window),
                        "count": lat_count,
                        "p50Ms": p50 * 1e3 if p50 is not None else None,
                        "p99Ms": p99 * 1e3 if p99 is not None else None,
                        "p999Ms": p999 * 1e3 if p999 is not None else None,
                    },
                    "alerts": alerts,
                    "latencyOk": latency_ok,
                    "ok": ok,
                }
        return {
            "slotSeconds": self.slot_seconds,
            "latencyWindow": _window_name(self.latency_window),
            "budgetPeriod": _window_name(self.budget_period),
            "burnRules": [r.to_dict() for r in self.burn_rules],
            "uptimeSeconds": now - self.started,
            "classes": out_classes,
        }

    def pressure(self) -> dict:
        """Control-loop tap for the QoS governor (server/qos.py):
        which objective-bearing classes are burning (any rule firing)
        or violating their latency objective right now.  Derived from
        the live snapshot — tenant-scoped classes (``op@tenant``)
        appear here like any other, which is what lets the ladder see
        a single victim's budget burning."""
        snap = self.snapshot()
        alerts: list[tuple[str, str]] = []
        latency: list[str] = []
        for name, c in snap["classes"].items():
            if c["objective"] is None:
                continue
            for rule, firing in c["alerts"].items():
                if firing:
                    alerts.append((name, rule))
            if c["latencyOk"] is False:
                latency.append(name)
        return {"alerts": alerts, "latency": latency}

    def summary(self) -> dict:
        """Compact block for /debug/vars: totals and verdicts only."""
        snap = self.snapshot()
        return {
            "classes": {
                name: {
                    "total": c["total"],
                    "errors": c["errors"],
                    "p99Ms": c["latency"]["p99Ms"],
                    "ok": c["ok"],
                    "alerts": c["alerts"],
                }
                for name, c in snap["classes"].items()
            },
            "burnRules": snap["burnRules"],
        }

    def prometheus_text(self, exemplar_filter=None) -> str:
        """``pilosa_slo_*`` series for the /metrics scrape.  Rendered
        directly from the tracker (no MemStatsClient round trip): the
        windowed gauges are recomputed at scrape time and the counters
        are monotone from the lifetime totals.  With ``exemplar_filter``
        the per-class duration histogram carries OpenMetrics
        ``# {trace_id="..."}`` exemplars for tail-kept traces."""
        snap = self.snapshot()
        out: list[str] = []

        def typ(name: str, t: str) -> None:
            out.append(f"# TYPE {name} {t}")

        typ("pilosa_slo_requests_total", "counter")
        for name, c in snap["classes"].items():
            out.append(
                f'pilosa_slo_requests_total{{class="{name}"}} {c["total"]}'
            )
        typ("pilosa_slo_errors_total", "counter")
        for name, c in snap["classes"].items():
            out.append(
                f'pilosa_slo_errors_total{{class="{name}"}} {c["errors"]}'
            )
        typ("pilosa_slo_objective_availability", "gauge")
        for name, c in snap["classes"].items():
            if c["objective"] is not None:
                out.append(
                    f'pilosa_slo_objective_availability{{class="{name}"}}'
                    f' {c["objective"]["availability"]}'
                )
        typ("pilosa_slo_availability", "gauge")
        for name, c in snap["classes"].items():
            for wname, w in c["windows"].items():
                out.append(
                    f'pilosa_slo_availability{{class="{name}",window="{wname}"}}'
                    f' {w["availability"]}'
                )
        typ("pilosa_slo_burn_rate", "gauge")
        for name, c in snap["classes"].items():
            for wname, w in c["windows"].items():
                if "burnRate" in w:
                    out.append(
                        f'pilosa_slo_burn_rate{{class="{name}",window="{wname}"}}'
                        f' {w["burnRate"]}'
                    )
        typ("pilosa_slo_error_budget_consumed", "gauge")
        for name, c in snap["classes"].items():
            for wname, w in c["windows"].items():
                if "budgetConsumed" in w:
                    out.append(
                        "pilosa_slo_error_budget_consumed"
                        f'{{class="{name}",window="{wname}"}}'
                        f' {w["budgetConsumed"]}'
                    )
        typ("pilosa_slo_latency_seconds", "gauge")
        for name, c in snap["classes"].items():
            lat = c["latency"]
            for q, key in (("0.5", "p50Ms"), ("0.99", "p99Ms"), ("0.999", "p999Ms")):
                v = lat[key]
                if v is not None:
                    out.append(
                        f'pilosa_slo_latency_seconds{{class="{name}",quantile="{q}"}}'
                        f" {v / 1e3}"
                    )
        typ("pilosa_slo_alert", "gauge")
        for name, c in snap["classes"].items():
            for rule, firing in c["alerts"].items():
                out.append(
                    f'pilosa_slo_alert{{class="{name}",rule="{rule}"}}'
                    f" {1 if firing else 0}"
                )
        # Lifetime per-class duration histogram (distinct name from the
        # pilosa_slo_latency_seconds quantile gauges above): the series
        # that carries bucket exemplars pointing into /debug/traces.
        from pilosa_tpu.obs.stats import exemplar_suffix

        with self._lock:
            hist = {
                name: (list(st.lat_buckets), st.lat_sum, list(st.exemplars))
                for name, st in self._classes.items()
            }
        typ("pilosa_slo_request_duration_seconds", "histogram")
        base = "pilosa_slo_request_duration_seconds"
        for name in sorted(hist):
            buckets, total, exemplars = hist[name]
            cum = 0
            for i, bound in enumerate(LATENCY_BOUNDS):
                cum += buckets[i]
                ex = exemplar_suffix(exemplars[i], exemplar_filter)
                out.append(
                    f'{base}_bucket{{class="{name}",le="{bound}"}} {cum}{ex}'
                )
            cum += buckets[-1]
            ex = exemplar_suffix(exemplars[-1], exemplar_filter)
            out.append(f'{base}_bucket{{class="{name}",le="+Inf"}} {cum}{ex}')
            out.append(f'{base}_count{{class="{name}"}} {cum}')
            out.append(f'{base}_sum{{class="{name}"}} {total}')
        return "\n".join(out) + "\n"


def objectives_from_dict(spec: dict) -> dict[str, Objective]:
    """Build an objectives map from a plain-dict config (NodeServer /
    InProcessCluster knob): ``{class: {"availability": 0.999,
    "latencyP99Ms": 50}}``.  Starts from the defaults; a class mapped
    to None drops its objective.

    The PER-TENANT dimension rides a ``"tenants"`` sub-spec::

        {"tenants": {"victim": {"read.count": {"availability": 0.99,
                                               "latencyP99Ms": 500}}}}

    which expands to tenant-scoped classes (``read.count@victim``) —
    the tracker then budgets that tenant's traffic separately and the
    QoS pressure ladder can defend it by name."""
    spec = dict(spec or {})
    tenants = spec.pop("tenants", None) or {}
    out = dict(DEFAULT_OBJECTIVES)

    def build(o):
        lat_ms = o.get("latencyP99Ms")
        return Objective(
            o.get("availability", 0.999),
            lat_ms / 1e3 if lat_ms is not None else None,
        )

    for name, o in spec.items():
        if o is None:
            out.pop(name, None)
            continue
        out[name] = build(o)
    for tenant, classes in tenants.items():
        for name, o in (classes or {}).items():
            key = tenant_class(name, tenant)
            if o is None:
                out.pop(key, None)
                continue
            out[key] = build(o)
    return out
