"""Cluster event journal: a bounded ring of typed control-plane events.

The reference exposes its control plane through log lines and the
diagnostics phone-home payload; debugging a production cluster means
asking a node "what happened here in the last hour" — membership churn,
resize phases, anti-entropy rounds, breaker flips, snapshot compactions,
injected faults.  This journal is that surface: every control-plane
subsystem records typed events into a per-node ring buffer with
monotonic sequence numbers, served at ``/debug/events?since=<seq>``.

Cursor semantics: sequence numbers start at 1 and never repeat.  A
consumer polls ``since=<last nextSeq>`` and is guaranteed gap-free,
duplicate-free delivery as long as it keeps up with the ring; when the
ring has dropped events past the cursor the response says so
(``truncated``) instead of silently skipping — the consumer knows its
timeline has a hole rather than believing a quiet cluster.

The coordinator's ``/debug/events?cluster=true`` view fans out to every
peer and merges the per-node journals into one cluster timeline ordered
by wall-clock time (each event keeps its origin node id and per-node
seq, so per-node ordering is still exact even when clocks skew).
"""

from __future__ import annotations

import threading
import time
from collections import deque

# -- event types -------------------------------------------------------------

EVENT_NODE_START = "node-start"          # this process came up
EVENT_MEMBERSHIP_SET = "membership-set"  # static membership fixed at join
EVENT_NODE_JOIN = "node-join"            # a member appeared in a commit
EVENT_NODE_LEAVE = "node-leave"          # a member left in a commit
EVENT_NODE_STATE = "node-state"          # peer READY/DOWN transition
EVENT_CLUSTER_STATE = "cluster-state"    # NORMAL/DEGRADED/RESIZING/...
EVENT_RESIZE_START = "resize-start"
EVENT_RESIZE_PHASE = "resize-phase"
EVENT_RESIZE_COMMIT = "resize-commit"
EVENT_RESIZE_ABORT = "resize-abort"
EVENT_RESIZE_RESUME = "resize-resume"      # journaled plan re-dispatched
EVENT_RESIZE_DATA_LOSS = "resize-data-loss"  # dead removal dropped fragments
EVENT_RESIZE_WATCHDOG = "resize-watchdog"  # node self-healed a missed commit
EVENT_MIGRATE_FRAGMENT = "migrate-fragment"  # one fragment's migration done
EVENT_EPOCH_FLIP = "epoch-flip"            # per-shard ownership flipped
EVENT_ANTIENTROPY_ROUND = "antientropy-round"
EVENT_CIRCUIT_BREAKER = "circuit-breaker"
EVENT_SNAPSHOT = "snapshot"              # fragment op-log compaction
EVENT_FAULT_INJECTED = "fault-injected"  # testing/faults.py rule fired
EVENT_INCIDENT = "incident"              # flight recorder auto-capture
EVENT_QOS = "qos-transition"             # pressure-ladder stage change
EVENT_NODE_STOP = "node-stop"            # orderly shutdown began
EVENT_NODE_CRASH = "node-crash-detected"  # previous life died dirty


class EventJournal:
    """Thread-safe bounded ring of typed events with monotonic seqs."""

    def __init__(self, capacity: int = 1024, node_id: str = ""):
        self.capacity = max(1, int(capacity))
        self.node_id = node_id  # settable later, once the id is known
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._seq = 0
        self.dropped = 0  # events evicted by the ring bound

    # -- producers -----------------------------------------------------------

    def record(self, type: str, **data) -> dict:
        """Append one event; returns it (already sealed — callers must
        not mutate).  Never raises: the journal is an observability
        sink, and a failed record must not take down the subsystem
        that emitted it."""
        with self._lock:
            self._seq += 1
            event = {
                "seq": self._seq,
                "ts": time.time(),
                "node": self.node_id,
                "type": type,
                "data": data,
            }
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append(event)
            return event

    # -- consumers -----------------------------------------------------------

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def since(self, seq: int = 0, limit: int | None = None) -> dict:
        """Events with sequence number strictly greater than ``seq``.

        Returns ``{"events", "nextSeq", "firstSeq", "lastSeq",
        "truncated"}``.  ``nextSeq`` is the cursor for the next poll
        (pass it back as ``since=``).  ``truncated`` is True when the
        ring evicted events the cursor never saw — the consumer's
        timeline has a gap it should surface, not paper over.  With
        ``limit``, at most that many events return and ``nextSeq``
        points at the last one delivered, so a chunked consumer resumes
        without gaps or duplicates."""
        seq = max(0, int(seq))
        with self._lock:
            events = [e for e in self._ring if e["seq"] > seq]
            oldest = self._ring[0]["seq"] if self._ring else self._seq + 1
            # The cursor missed events iff some seq in (seq, oldest)
            # existed but was evicted.
            truncated = seq + 1 < oldest and self._seq >= oldest
            last = self._seq
        if limit is not None and len(events) > max(0, int(limit)):
            events = events[: max(0, int(limit))]
        next_seq = events[-1]["seq"] if events else max(seq, 0)
        if not events and seq < last:
            next_seq = last  # everything past the cursor was evicted
        return {
            "events": events,
            "nextSeq": next_seq,
            "firstSeq": oldest if events or truncated else None,
            "lastSeq": last,
            "truncated": truncated,
        }

    def snapshot_summary(self) -> dict:
        """Cheap block for /debug/vars."""
        with self._lock:
            return {
                "lastSeq": self._seq,
                "retained": len(self._ring),
                "capacity": self.capacity,
                "dropped": self.dropped,
            }


def merge_timelines(per_node: list[list[dict]]) -> list[dict]:
    """Merge several nodes' event lists into one timeline ordered by
    wall-clock time (ties broken by node id then per-node seq, so the
    merge is deterministic under clock skew)."""
    merged = [e for events in per_node for e in events]
    merged.sort(key=lambda e: (e.get("ts", 0.0), e.get("node", ""), e.get("seq", 0)))
    return merged
