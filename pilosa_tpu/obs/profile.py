"""In-process CPU sampling profiler and memory snapshot.

The reference mounts net/http/pprof on its router (reference
http/handler.go:280) and enables block/mutex profile rates
(server.go:184-185); the analogues here are:

* ``sample(seconds)`` — a statistical wall-clock sampler over
  ``sys._current_frames()``: every tick it records the collapsed stack
  of EVERY live thread (cProfile would only see the calling thread,
  which is never the one serving queries).  Output is
  flamegraph-collapsed format ("a;b;c count" lines), the same shape
  ``go tool pprof``'s raw dumps collapse to.
* ``memory_snapshot(holder)`` — RSS + per-component accounting: host
  mirror bytes by index, device (HBM) budget state, GC and thread
  counts — the heap-profile role, shaped to this runtime's actual
  memory owners (numpy mirrors and HBM stacks, which a Python heap
  profiler cannot see).
"""

from __future__ import annotations

import gc
import os
import sys
import threading
import time
from collections import Counter


def _collapse(frame) -> str:
    parts: list[str] = []
    while frame is not None:
        code = frame.f_code
        parts.append(f"{os.path.basename(code.co_filename)}:{code.co_name}")
        frame = frame.f_back
    return ";".join(reversed(parts))


def sample(
    seconds: float, interval: float = 0.005, max_seconds: float = 30.0
) -> dict:
    """Sample all threads' stacks for ``seconds`` (capped); returns
    {"samples": N, "seconds": s, "interval_s": i,
     "stacks": {collapsed_stack: count}, "threads": {name: count}}."""
    seconds = max(0.05, min(float(seconds), max_seconds))
    me = threading.get_ident()
    names = {}
    stacks: Counter[str] = Counter()
    per_thread: Counter[str] = Counter()
    n = 0
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        for t in threading.enumerate():
            names[t.ident] = t.name
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue  # the sampler itself is noise
            stacks[_collapse(frame)] += 1
            per_thread[names.get(ident, str(ident))] += 1
        n += 1
        time.sleep(interval)
    return {
        "samples": n,
        "seconds": seconds,
        "interval_s": interval,
        "stacks": dict(stacks.most_common()),
        "threads": dict(per_thread.most_common()),
    }


def memory_snapshot(holder=None) -> dict:
    """Process + framework memory accounting (the heap-profile role)."""
    from pilosa_tpu.core import membudget
    from pilosa_tpu.obs.sysinfo import SystemInfo

    out: dict = {
        "rss_bytes": SystemInfo().process_rss(),
        "gc_counts": gc.get_count(),
        "gc_collections": [s.get("collections") for s in gc.get_stats()],
        "threads": threading.active_count(),
    }
    b = membudget.default_budget()
    out["hbm_budget"] = {
        "cap_bytes": b.cap,
        "used_bytes": b.used(),
        "entries": b.entry_count(),
        "evictions": b.evictions,
        "admissions": b.admissions,
    }
    if holder is not None:
        per_index = {}
        total = 0
        frags = 0
        for idx in list(holder.indexes.values()):
            ibytes = 0
            for field in list(idx.fields.values()):
                for view in list(field.views.values()):
                    for frag in list(view.fragments.values()):
                        host = getattr(frag, "_host", None)
                        if host is not None:
                            ibytes += host.nbytes
                        frags += 1
            per_index[idx.name] = ibytes
            total += ibytes
        out["host_mirrors"] = {
            "total_bytes": total,
            "fragments": frags,
            "by_index": per_index,
        }
    return out
