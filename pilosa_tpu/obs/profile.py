"""In-process CPU sampling profiler and memory snapshot.

The reference mounts net/http/pprof on its router (reference
http/handler.go:280) and enables block/mutex profile rates
(server.go:184-185); the analogues here are:

* ``sample(seconds)`` — a statistical wall-clock sampler over
  ``sys._current_frames()``: every tick it records the collapsed stack
  of EVERY live thread (cProfile would only see the calling thread,
  which is never the one serving queries).  Output is
  flamegraph-collapsed format ("a;b;c count" lines), the same shape
  ``go tool pprof``'s raw dumps collapse to.
* ``memory_snapshot(holder)`` — RSS + per-component accounting: host
  mirror bytes by index, device (HBM) budget state, GC and thread
  counts — the heap-profile role, shaped to this runtime's actual
  memory owners (numpy mirrors and HBM stacks, which a Python heap
  profiler cannot see).
"""

from __future__ import annotations

import gc
import os
import sys
import threading
import time
from collections import Counter


def _collapse(frame) -> str:
    parts: list[str] = []
    while frame is not None:
        code = frame.f_code
        parts.append(f"{os.path.basename(code.co_filename)}:{code.co_name}")
        frame = frame.f_back
    return ";".join(reversed(parts))


class Sampler:
    """Incremental all-thread stack sampler: call :meth:`tick` at any
    cadence (the blocking :func:`sample` loop, or the flight recorder's
    segment thread), :meth:`drain` to take the accumulated collapse and
    reset.  One tick walks ``sys._current_frames()`` once — the
    Google-Wide-Profiling shape: always-on because each observation is
    O(live threads), not O(wall time)."""

    def __init__(self, exclude_ident: int | None = None):
        self._exclude = exclude_ident
        self._names: dict[int | None, str] = {}
        self._stacks: Counter[str] = Counter()
        self._per_thread: Counter[str] = Counter()
        self.samples = 0

    def tick(self) -> None:
        for t in threading.enumerate():
            self._names[t.ident] = t.name
        me = threading.get_ident()
        for ident, frame in sys._current_frames().items():
            if ident == me or ident == self._exclude:
                continue  # the sampler itself is noise
            self._stacks[_collapse(frame)] += 1
            self._per_thread[self._names.get(ident, str(ident))] += 1
        self.samples += 1

    def drain(self, top: int | None = None) -> dict:
        """Take {"samples", "stacks", "threads"} and reset the counters;
        ``top`` bounds the stack list (segment records keep only the
        hottest stacks)."""
        out = {
            "samples": self.samples,
            "stacks": dict(self._stacks.most_common(top)),
            "threads": dict(self._per_thread.most_common()),
        }
        self._stacks.clear()
        self._per_thread.clear()
        self.samples = 0
        return out


def sample(
    seconds: float, interval: float = 0.005, max_seconds: float = 30.0
) -> dict:
    """Sample all threads' stacks for ``seconds`` (capped); returns
    {"samples": N, "seconds": s, "interval_s": i,
     "stacks": {collapsed_stack: count}, "threads": {name: count}}."""
    seconds = max(0.05, min(float(seconds), max_seconds))
    s = Sampler()
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        s.tick()
        time.sleep(interval)
    out = s.drain()
    out.update(seconds=seconds, interval_s=interval)
    return out


def memory_snapshot(holder=None) -> dict:
    """Process + framework memory accounting (the heap-profile role)."""
    from pilosa_tpu.core import membudget
    from pilosa_tpu.obs.sysinfo import SystemInfo

    out: dict = {
        "rss_bytes": SystemInfo().process_rss(),
        "gc_counts": gc.get_count(),
        "gc_collections": [s.get("collections") for s in gc.get_stats()],
        "threads": threading.active_count(),
    }
    b = membudget.default_budget()
    out["hbm_budget"] = {
        "cap_bytes": b.cap,
        "used_bytes": b.used(),
        "entries": b.entry_count(),
        "evictions": b.evictions,
        "admissions": b.admissions,
    }
    if holder is not None:
        per_index = {}
        total = 0
        frags = 0
        for idx in list(holder.indexes.values()):
            ibytes = 0
            for field in list(idx.fields.values()):
                for view in list(field.views.values()):
                    for frag in list(view.fragments.values()):
                        host = getattr(frag, "_host", None)
                        if host is not None:
                            ibytes += host.nbytes
                        frags += 1
            per_index[idx.name] = ibytes
            total += ibytes
        out["host_mirrors"] = {
            "total_bytes": total,
            "fragments": frags,
            "by_index": per_index,
        }
    return out
