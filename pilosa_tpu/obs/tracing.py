"""Distributed tracing (reference: tracing/tracing.go:22-50 Tracer/Span
interface + global tracer, tracing/opentracing/opentracing.go:31-76
Jaeger adapter with HTTP header inject/extract for cross-node traces).

The reference instruments ~80 spans across the executor, fragment
imports, API, and syncers via ``tracing.StartSpanFromContext``. Here the
active span is carried in a ``contextvars.ContextVar`` (the Python
analogue of ctx-carried spans), with explicit header inject/extract at
the node boundary so a query fanned out over HTTP appears as one trace:

    coordinator: api.query span  ─ inject → X-Trace-Id/X-Span-Id headers
    remote node: extract → handler span (child, same trace id)

Backends: :class:`NopTracer` (zero-cost default, like the reference's
default no-op tracer) and :class:`RecordingTracer` (in-process ring
buffer — the stand-in for the Jaeger agent exporter, which needs
network egress; spans can be dumped for offline analysis).
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
from collections import deque

from pilosa_tpu.obs import qprofile

TRACE_HEADER = "X-Pilosa-Trace-Id"
SPAN_HEADER = "X-Pilosa-Span-Id"
TRACEPARENT_HEADER = "traceparent"

# Id minting (W3C trace-context widths: 128-bit trace ids, 64-bit span
# ids).  A per-process RNG — NOT a counter — so two nodes never mint the
# same trace id; ``seed_ids`` re-seeds it for deterministic tests.
_id_lock = threading.Lock()
_id_rng = random.Random()


def seed_ids(seed: int | None) -> None:
    """Re-seed the id generator (tests); ``None`` restores entropy."""
    with _id_lock:
        _id_rng.seed(seed)


def _new_trace_id() -> int:
    with _id_lock:
        while True:
            tid = _id_rng.getrandbits(128)
            if tid:  # the zero id is invalid on the wire (W3C §3.2.2.3)
                return tid


def _new_span_id() -> int:
    with _id_lock:
        while True:
            sid = _id_rng.getrandbits(64)
            if sid:
                return sid


_active_span: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "pilosa_active_span", default=None
)

# Optional span sink: called with every finished span AFTER the tracer's
# own ``_record``.  This is how the per-node TraceStore observes spans
# without replacing the configured tracer (obs/tracestore.py installs
# itself here at import-time of the store module).
_span_sink = None


def set_span_sink(sink) -> None:
    global _span_sink
    _span_sink = sink


class SpanContext:
    """Wire-propagatable identity of a span.  ``remote`` marks a context
    extracted from incoming headers: a span whose parent is remote is a
    *local root* — the first span of this trace on this node — which is
    where tail-sampling decisions attach."""

    __slots__ = ("trace_id", "span_id", "remote")

    def __init__(self, trace_id: int, span_id: int, remote: bool = False):
        self.trace_id = trace_id
        self.span_id = span_id
        self.remote = remote


class Span:
    """One timed operation (reference tracing.Span :44-50)."""

    def __init__(self, tracer: "Tracer", name: str, parent: SpanContext | None):
        self.tracer = tracer
        self.name = name
        self.parent_id = parent.span_id if parent else 0
        # local root = no parent at all, or a parent extracted from the
        # wire (the first span of the trace on THIS node)
        self.local_root = parent is None or parent.remote
        trace_id = parent.trace_id if parent else _new_trace_id()
        self.context = SpanContext(trace_id, _new_span_id())
        self.start = time.monotonic()
        # wall-clock anchor, taken once at span start: exporters must not
        # re-derive it at export time (batched exports would skew it)
        self.start_unix_ns = time.time_ns()
        self.duration = None
        self.tags: dict = {}
        self._token = None
        self._phandle = None

    def set_tag(self, key: str, value) -> "Span":
        self.tags[key] = value
        return self

    def log_kv(self, **fields) -> None:
        self.tags.setdefault("logs", []).append((time.monotonic(), fields))

    def finish(self) -> None:
        if self.duration is None:
            self.duration = time.monotonic() - self.start
            self.tracer._record(self)
            if _span_sink is not None:
                _span_sink(self)

    # context-manager + ambient-activation protocol.  Every span is
    # also mirrored into the active QueryProfile (if any) — this runs
    # for the NopTracer too, which is how ``?profile=true`` sees the
    # call tree without a tracing backend configured.
    def __enter__(self) -> "Span":
        self._token = _active_span.set(self)
        self._phandle = qprofile.span_enter(self.name)
        return self

    def __exit__(self, *exc) -> None:
        qprofile.span_exit(self._phandle, self.tags)
        self._phandle = None
        if self._token is not None:
            _active_span.reset(self._token)
            self._token = None
        self.finish()


class Tracer:
    """reference tracing.Tracer :32-41."""

    def start_span(
        self, name: str, child_of: SpanContext | None = None
    ) -> Span:
        if child_of is None:
            parent = _active_span.get()
            child_of = parent.context if parent is not None else None
        return Span(self, name, child_of)

    def inject_headers(self, ctx: SpanContext, headers: dict) -> None:
        """opentracing.go:58-66 InjectHTTPHeaders — native headers plus a
        W3C ``traceparent`` (version 00, sampled flag set) for interop."""
        headers[TRACE_HEADER] = str(ctx.trace_id)
        headers[SPAN_HEADER] = str(ctx.span_id)
        headers[TRACEPARENT_HEADER] = format_traceparent(ctx)

    def extract_headers(self, headers) -> SpanContext | None:
        """opentracing.go:68-76 ExtractHTTPHeaders.  Native headers win;
        falls back to W3C ``traceparent``."""
        trace_id = headers.get(TRACE_HEADER)
        span_id = headers.get(SPAN_HEADER)
        if trace_id and span_id:
            try:
                return SpanContext(int(trace_id), int(span_id), remote=True)
            except ValueError:
                return None
        return parse_traceparent(headers.get(TRACEPARENT_HEADER))

    def _record(self, span: Span) -> None:
        pass


class NopTracer(Tracer):
    pass


class RecordingTracer(Tracer):
    """Ring-buffer recorder (Jaeger-exporter stand-in)."""

    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self.spans: deque[Span] = deque(maxlen=capacity)

    def _record(self, span: Span) -> None:
        with self._lock:
            self.spans.append(span)

    def finished(self, name: str | None = None) -> list[Span]:
        with self._lock:
            return [s for s in self.spans if name is None or s.name == name]

    def traces(self) -> dict[int, list[Span]]:
        """Finished spans grouped by trace id, in finish order."""
        with self._lock:
            out: dict[int, list[Span]] = {}
            for s in self.spans:
                out.setdefault(s.context.trace_id, []).append(s)
            return out


class ExportingTracer(RecordingTracer):
    """Samples spans at the root and forwards finished spans to an
    exporter (reference tracing/opentracing/opentracing.go:31-76 Jaeger
    adapter + sampler config server/config.go:139-145).

    Sampling is head-based per trace: the root span's trace id decides,
    so a trace is exported whole or not at all."""

    def __init__(self, exporter, sample_rate: float = 1.0, capacity: int = 4096):
        super().__init__(capacity)
        self.exporter = exporter
        self.sample_rate = max(0.0, min(1.0, sample_rate))

    def _sampled(self, trace_id: int) -> bool:
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        # cheap deterministic hash of the trace id -> [0, 1)
        return ((trace_id * 2654435761) & 0xFFFFFFFF) / 2**32 < self.sample_rate

    def _record(self, span: Span) -> None:
        super()._record(span)
        if self._sampled(span.context.trace_id):
            self.exporter.export(span)

    def close(self) -> None:
        self.exporter.close()


def format_traceparent(ctx: SpanContext) -> str:
    """W3C trace-context header: 00-<32hex trace>-<16hex span>-<flags>."""
    return f"00-{ctx.trace_id & (2**128 - 1):032x}-{ctx.span_id & (2**64 - 1):016x}-01"


def parse_traceparent(value) -> SpanContext | None:
    """Parse a W3C ``traceparent`` header; ``None`` on anything invalid
    (wrong field widths, non-hex, all-zero ids, reserved version ff)."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) < 4:
        return None
    version, trace_hex, span_hex = parts[0], parts[1], parts[2]
    if len(version) != 2 or len(trace_hex) != 32 or len(span_hex) != 16:
        return None
    if version.lower() == "ff":
        return None
    try:
        int(version, 16)
        trace_id = int(trace_hex, 16)
        span_id = int(span_hex, 16)
    except ValueError:
        return None
    if not trace_id or not span_id:
        return None
    return SpanContext(trace_id, span_id, remote=True)


# Global tracer (reference tracing.GlobalTracer :22-29).
_global = Tracer.__new__(NopTracer)  # type: ignore[assignment]


def get_tracer() -> Tracer:
    return _global


def set_tracer(t: Tracer) -> None:
    global _global
    _global = t


def start_span(name: str, child_of: SpanContext | None = None) -> Span:
    """reference tracing.StartSpanFromContext — ambient parenting via the
    context variable when ``child_of`` is not given."""
    return _global.start_span(name, child_of)


def active_span() -> Span | None:
    return _active_span.get()
