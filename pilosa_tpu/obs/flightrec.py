"""Always-on flight recorder + incident engine (the third observability
plane's capture side).

Google-Wide-Profiling shape: a background thread continuously samples
every thread's stack at a low rate and rolls the collapse into ~1 s
*segments*, each also carrying the serving plane's congestion signals
(batcher depth peak, kernel dispatch deltas, ingest occupancy, per-peer
circuit-breaker state, deadline-504 delta).  The segment ring is small
and bounded — the point is not history, it is that when something goes
wrong the *preceding* seconds are already captured.

The incident engine watches two signals at segment cadence:

* SLO burn-rate alert edges — a (class, rule) alert transitioning
  false→true (SRE-Workbook multiwindow alerts from obs/slo.py).  While
  any alert stays firing, further edges join the same episode: one burn
  = one incident, however many rules it trips on the way down.
* deadline-504 spikes — ``http_deadline_exceeded`` jumping by more than
  a threshold within one segment (re-armed by a clean segment).

On trigger it freezes a bounded *bundle*: the last N segments, the
trace store's kept traces (the slow/erroring evidence), the slow-query
log, and the SLO verdicts — served at ``GET /debug/incidents`` and
journaled as an ``incident`` control-plane event.
"""

from __future__ import annotations

import threading
import time
import uuid

from pilosa_tpu.obs import events as ev
from pilosa_tpu.obs import profile

# stacks kept per segment: enough for attribution, bounded for the ring
_SEGMENT_TOP_STACKS = 20


class FlightRecorder:
    def __init__(
        self,
        holder,
        api=None,
        client=None,
        segment_seconds: float = 1.0,
        sample_interval: float = 0.025,
        segments: int = 60,
        incident_capacity: int = 8,
        incident_segments: int = 10,
        incident_traces: int = 16,
        spike_504: int = 5,
    ):
        self.holder = holder
        self.api = api
        self.client = client
        self.segment_seconds = max(0.05, float(segment_seconds))
        self.sample_interval = max(0.001, float(sample_interval))
        self.max_segments = max(1, int(segments))
        self.incident_capacity = max(1, int(incident_capacity))
        self.incident_segments = max(1, int(incident_segments))
        self.incident_traces = max(1, int(incident_traces))
        self.spike_504 = max(1, int(spike_504))
        # optional hook (obs/history.py): callable(trigger) -> dict of
        # series windows frozen into the bundle, so an incident carries
        # its own recent history instead of just the moment of the edge
        self.series_provider = None
        # optional hook (obs/blackbox.py): callable(bundle) invoked
        # after a bundle freezes, so the black box can flush it to disk
        # synchronously — an incident is when the process is likeliest
        # to die next
        self.on_incident = None
        self._lock = threading.Lock()
        self._segments: list[dict] = []
        self._incidents: list[dict] = []
        self._seq = 0
        # incident-engine state (loop thread only)
        self._firing: set[tuple[str, str]] = set()
        self._last_504 = None  # counter baseline; None until first segment
        self._spike_armed = True
        self._last_dispatch = None
        self._last_devcosts = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        # Baseline the 504 counter NOW: a spike inside the first segment
        # window must not be swallowed as the baseline.
        stats = self.holder.stats
        if self._last_504 is None and hasattr(stats, "get_counter"):
            self._last_504 = stats.get_counter("http_deadline_exceeded")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="flight-recorder", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout=5.0)
        self._thread = None

    # -- recorder loop -------------------------------------------------------

    def _loop(self) -> None:
        sampler = profile.Sampler(exclude_ident=threading.get_ident())
        while not self._stop.is_set():
            seg_start = time.monotonic()
            seg_end = seg_start + self.segment_seconds
            while not self._stop.is_set():
                sampler.tick()
                rem = seg_end - time.monotonic()
                if rem <= 0:
                    break
                self._stop.wait(min(self.sample_interval, rem))
            try:
                seg = self._segment(sampler, time.monotonic() - seg_start)
                self._record_segment(seg)
                self._check_incidents(seg)
            except Exception:  # graftlint: disable=exception-hygiene -- the recorder must outlive any one bad snapshot source
                sampler.drain()  # never let a failed segment accumulate

    def _segment(self, sampler, elapsed: float) -> dict:
        self._seq += 1
        seg = {
            "seq": self._seq,
            "at": time.time(),
            "seconds": round(elapsed, 3),
            "profile": sampler.drain(top=_SEGMENT_TOP_STACKS),
        }
        api = self.api
        batcher = getattr(api, "batcher", None) if api is not None else None
        if batcher is not None:
            snap = batcher.snapshot()
            snap["depthPeak"] = batcher.take_depth_peak()
            seg["batcher"] = snap
        ingest = getattr(api, "ingest", None) if api is not None else None
        if ingest is not None:
            seg["ingest"] = ingest.snapshot()
        try:
            from pilosa_tpu.ops import kernels

            lanes = kernels.telemetry_snapshot().get("dispatch_lanes", {})
            total = sum(lanes.values())
            if self._last_dispatch is None:
                self._last_dispatch = total
            seg["kernelDispatchDelta"] = total - self._last_dispatch
            self._last_dispatch = total
        except Exception:  # graftlint: disable=exception-hygiene -- kernel telemetry is optional on CPU-only builds
            pass
        try:
            from pilosa_tpu.obs import devledger

            dev = devledger.counters()
            cur = {
                "compiles": dev["compiles"],
                "launches": dev["launches"],
                "transferBytes": dev["h2dBytes"] + dev["d2hBytes"],
            }
            last = self._last_devcosts or cur
            seg["devledgerDelta"] = {
                k: cur[k] - last[k] for k in cur
            }
            self._last_devcosts = cur
        except Exception:  # graftlint: disable=exception-hygiene -- ledger deltas are advisory segment context
            pass
        client = self.client
        if client is not None and hasattr(client, "breaker_states"):
            breakers = client.breaker_states()
            if breakers:
                seg["breakers"] = breakers
        stats = self.holder.stats
        if hasattr(stats, "get_counter"):
            total_504 = stats.get_counter("http_deadline_exceeded")
            if self._last_504 is None:
                self._last_504 = total_504
            seg["deadline504Delta"] = total_504 - self._last_504
            self._last_504 = total_504
        return seg

    def _record_segment(self, seg: dict) -> None:
        with self._lock:
            self._segments.append(seg)
            if len(self._segments) > self.max_segments:
                del self._segments[: len(self._segments) - self.max_segments]

    # -- incident engine -----------------------------------------------------

    def _check_incidents(self, seg: dict) -> None:
        firing_now: set[tuple[str, str]] = set()
        try:
            snap = self.holder.slo.snapshot()
            for cname, c in snap["classes"].items():
                for rule, firing in c.get("alerts", {}).items():
                    if firing:
                        firing_now.add((cname, rule))
        except Exception:  # graftlint: disable=exception-hygiene -- a broken snapshot must not kill the recorder
            snap = None
        new_edges = firing_now - self._firing
        was_quiet = not self._firing
        self._firing = firing_now
        if new_edges and was_quiet:
            # one burn episode = one incident: further rules tripping
            # while any alert is still firing join this episode
            cname, rule = sorted(new_edges)[0]
            self._capture(
                {"type": "slo-alert", "class": cname, "rule": rule,
                 "edges": sorted(f"{c}/{r}" for c, r in new_edges)},
                slo_snap=snap,
            )
            return
        delta = seg.get("deadline504Delta", 0)
        if delta >= self.spike_504 and self._spike_armed and was_quiet:
            self._spike_armed = False
            self._capture(
                {"type": "deadline-504-spike", "count": delta}, slo_snap=snap
            )
        elif delta == 0:
            self._spike_armed = True

    def _capture(self, trigger: dict, slo_snap=None) -> None:
        incident_id = uuid.uuid4().hex[:12]
        traces = getattr(self.holder, "traces", None)
        kept = []
        if traces is not None:
            kept = traces.summaries(self.incident_traces)
        slow = None
        if self.api is not None:
            slow = self.api.slow_queries.snapshot()
        with self._lock:
            segments = list(self._segments[-self.incident_segments:])
        bundle = {
            "id": incident_id,
            "at": time.time(),
            "node": getattr(traces, "node_id", ""),
            "trigger": trigger,
            "segments": segments,
            "traces": kept,
            "slowQueries": slow,
        }
        prov = self.series_provider
        if prov is not None:
            try:
                series = prov(trigger)
                if series:
                    bundle["series"] = series
            except Exception:  # graftlint: disable=exception-hygiene -- history attachment is best-effort
                pass
        if slo_snap is not None:
            bundle["slo"] = {
                name: {
                    "alerts": c["alerts"],
                    "total": c["total"],
                    "errors": c["errors"],
                    "p99Ms": c["latency"]["p99Ms"],
                }
                for name, c in slo_snap["classes"].items()
            }
        with self._lock:
            self._incidents.append(bundle)
            if len(self._incidents) > self.incident_capacity:
                del self._incidents[: len(self._incidents)
                                    - self.incident_capacity]
        try:
            # the trigger's "type" key would collide with record()'s
            # event-type parameter; journal it as "trigger"
            self.holder.events.record(
                ev.EVENT_INCIDENT,
                id=incident_id,
                trigger=trigger["type"],
                **{k: v for k, v in trigger.items() if k != "type"},
            )
        except Exception:  # graftlint: disable=exception-hygiene -- journaling is best-effort
            pass
        hook = self.on_incident
        if hook is not None:
            try:
                hook(bundle)
            except Exception:  # graftlint: disable=exception-hygiene -- durable-flush wiring must not fail the capture
                pass

    def capture_incident(self, trigger: dict) -> None:
        """External incident trigger (the device ledger's recompile-storm
        callback): freeze a bundle around the current segments.  Safe to
        call from any thread; failures must not reach the caller.  A
        stopped recorder ignores triggers — the process-global ledger
        outlives individual nodes in multi-node test processes."""
        t = self._thread
        if t is None or not t.is_alive():
            return
        try:
            self._capture(dict(trigger))
        except Exception:  # graftlint: disable=exception-hygiene -- external triggers are best-effort
            pass

    # -- exposition ----------------------------------------------------------

    def incidents_snapshot(self) -> dict:
        with self._lock:
            incidents = [
                {k: v for k, v in b.items()
                 if k not in ("segments", "traces", "slowQueries", "series")}
                for b in reversed(self._incidents)
            ]
            return {
                "enabled": True,
                "segmentSeconds": self.segment_seconds,
                "segments": len(self._segments),
                "incidents": incidents,
            }

    def incident_detail(self, incident_id: str) -> dict | None:
        with self._lock:
            for b in self._incidents:
                if b["id"] == incident_id:
                    return dict(b)
        return None

    def segments_snapshot(self, limit: int = 10) -> list[dict]:
        with self._lock:
            return list(self._segments[-limit:])

    def incidents_full(self) -> list[dict]:
        """Every retained bundle WITH bodies, oldest first — the black
        box checkpoints these verbatim so a postmortem carries the same
        evidence ``/debug/incidents?id=`` would have served live."""
        with self._lock:
            return [dict(b) for b in self._incidents]
