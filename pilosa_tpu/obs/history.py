"""In-process retrospective metrics plane: a bounded ring-buffer TSDB
per holder plus EWMA trend detectors that promote sustained anomalies
into flight-recorder incidents.

Every other observability surface (/metrics, /debug/slo, /debug/qos,
/debug/devcosts, /debug/vars) is a point-in-time snapshot; without an
external Prometheus nothing can answer "what did p99 / batcher depth /
device-ms look like over the last ten minutes".  Monarch's answer —
in-memory time-series storage colocated with the serving process — is
the right shape at this scale: a background sampler (flight-recorder
style thread, ~1 s cadence) snapshots a curated set of series from the
existing planes into fixed-size numpy rings, with coarser retention
tiers produced by decimation (e.g. 5 m @ 1 s plus 1 h @ 15 s), so the
recent past is always queryable at ``GET /debug/history`` for the cost
of a few hundred KB per node.

Sample sequence numbers are monotonic and expressed in BASE-tier units
across every tier (a decimated tier's sample ``k`` covers base seqs
``[k*d, (k+1)*d)``), which gives ``?since=`` cursors the same
gap-honest contract as the event journal: a cursor that predates the
oldest retained sample comes back ``truncated`` instead of silently
skipping.

On top of the rings sits a trend-detector engine — EWMA-baseline
latency-regression, throughput-collapse, and error-acceleration — that
fires through the flight recorder's external-trigger path as ``trend``
incidents.  One trend episode = one incident (further series tripping
while any detector is latched join the episode), and the incident
bundle attaches the relevant series windows so the incident carries
its own history instead of just the moment of the edge.  Throughput
collapse deliberately treats rps == 0 as *no data*, not a collapse:
idle is indistinguishable from no offered load, and stage boundaries
in the load harness must not fire incidents.
"""

from __future__ import annotations

import fnmatch
import threading
import time

import numpy as np

# bounded exposition: recent trend triggers kept for /debug/history
_MAX_FIRED = 32

DETECTOR_LATENCY = "latency"
DETECTOR_THROUGHPUT = "throughput"
DETECTOR_ERRORS = "errors"
ALL_DETECTORS = (DETECTOR_LATENCY, DETECTOR_THROUGHPUT, DETECTOR_ERRORS)

# detector -> (series suffix it watches, human trigger name)
_DETECTOR_SUFFIX = {
    DETECTOR_LATENCY: (".p99_ms", "latency-regression"),
    DETECTOR_THROUGHPUT: (".rps", "throughput-collapse"),
    DETECTOR_ERRORS: (".eps", "error-acceleration"),
}


def parse_tiers(spec) -> list[tuple[int, int]]:
    """``"300@1,240@15"`` -> ``[(capacity, decimate), ...]`` sorted by
    decimation factor.  The finest tier must be undecimated (d == 1)
    and must retain at least one full decimation window for every
    coarser tier (coarse samples are folded from the base ring)."""
    if isinstance(spec, str):
        tiers = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            cap, _, dec = part.partition("@")
            tiers.append((int(cap), int(dec or 1)))
    else:
        tiers = [(int(c), int(d)) for c, d in spec]
    if not tiers:
        raise ValueError("history tiers: at least one tier required")
    tiers.sort(key=lambda t: t[1])
    if tiers[0][1] != 1:
        raise ValueError("history tiers: finest tier must have decimate=1")
    if any(c < 1 or d < 1 for c, d in tiers):
        raise ValueError(f"history tiers: bad spec {tiers!r}")
    if tiers[-1][1] > tiers[0][0]:
        raise ValueError(
            "history tiers: base capacity smaller than coarsest decimation"
        )
    return tiers


class _Tier:
    """One retention tier: a shared wall-clock ring plus one fixed-size
    value ring per series (NaN marks slots where a series had no
    sample).  ``count`` is the number of samples ever written."""

    def __init__(self, capacity: int, decimate: int):
        self.capacity = int(capacity)
        self.decimate = int(decimate)
        self.count = 0
        self.times = np.zeros(self.capacity, dtype=np.float64)
        self.values: dict[str, np.ndarray] = {}

    def append(self, wall: float, sample: dict) -> None:
        slot = self.count % self.capacity
        self.times[slot] = wall
        for name, arr in self.values.items():
            arr[slot] = sample.get(name, np.nan)
        for name, v in sample.items():
            if name not in self.values:
                arr = np.full(self.capacity, np.nan)
                arr[slot] = v
                self.values[name] = arr
        self.count += 1

    def window(self, start_idx: int):
        """(times, {name: values}) for tier samples [start_idx, count)."""
        idxs = np.arange(start_idx, self.count)
        slots = idxs % self.capacity
        return self.times[slots], {
            name: arr[slots] for name, arr in self.values.items()
        }


class _DetState:
    __slots__ = ("mean", "n", "bad", "good", "latched")

    def __init__(self):
        self.mean = None
        self.n = 0
        self.bad = 0
        self.good = 0
        self.latched = False


def _nanmean(win: np.ndarray) -> float:
    mask = ~np.isnan(win)
    if not mask.any():
        return float("nan")
    return float(win[mask].mean())


def downsample(points: list, step: float) -> list:
    """Mean-downsample ``[[t, v], ...]`` onto the wall-clock grid
    ``floor(t/step)*step`` (None values are gaps and are skipped; an
    all-gap bucket yields None).  The shared grid is what makes a
    cluster merge wall-clock ALIGNED: every node's points land in the
    same buckets regardless of sampler phase."""
    step = float(step)
    if step <= 0 or not points:
        return list(points)
    buckets: dict[float, list] = {}
    order: list[float] = []
    for t, v in points:
        b = float(np.floor(t / step) * step)
        if b not in buckets:
            buckets[b] = []
            order.append(b)
        if v is not None:
            buckets[b].append(v)
    out = []
    for b in sorted(order):
        vals = buckets[b]
        out.append([round(b, 3),
                    float(np.mean(vals)) if vals else None])
    return out


class MetricsHistory:
    """Bounded per-node metrics history + trend incident engine.

    The sampler thread calls :meth:`sample_once` (collect -> record);
    tests drive :meth:`record` directly with synthetic samples and
    explicit wall clocks, so ring/decimation/detector behaviour is
    deterministic without threads."""

    def __init__(
        self,
        holder,
        api=None,
        node_id: str = "",
        cadence: float = 1.0,
        tiers="300@1,240@15",
        detectors: str = "latency,throughput,errors",
        ewma_alpha: float = 0.1,
        warmup: int = 10,
        trips: int = 3,
        latency_factor: float = 2.0,
        latency_min_ms: float = 20.0,
        collapse_frac: float = 0.3,
        collapse_min_rps: float = 5.0,
        error_factor: float = 3.0,
        error_min_eps: float = 1.0,
    ):
        self.holder = holder
        self.api = api
        self.node_id = node_id or getattr(
            getattr(holder, "slo", None), "node_id", ""
        )
        self.cadence = max(0.01, float(cadence))
        specs = parse_tiers(tiers)
        self.tiers = [_Tier(c, d) for c, d in specs]
        if isinstance(detectors, str):
            detectors = [d.strip() for d in detectors.split(",") if d.strip()]
        self.detectors = frozenset(detectors) & set(ALL_DETECTORS)
        self.ewma_alpha = float(ewma_alpha)
        self.warmup = max(1, int(warmup))
        self.trips = max(1, int(trips))
        self.latency_factor = float(latency_factor)
        self.latency_min_ms = float(latency_min_ms)
        self.collapse_frac = float(collapse_frac)
        self.collapse_min_rps = float(collapse_min_rps)
        self.error_factor = float(error_factor)
        self.error_min_eps = float(error_min_eps)
        self.flightrec = None  # wired by NodeServer after both exist
        self._lock = threading.Lock()
        self._prev: dict[str, tuple[float, float]] = {}  # rate bookkeeping
        self._det: dict[tuple[str, str], _DetState] = {}
        self._episode_active = False
        self._fired: list[dict] = []
        self._samples_taken = 0
        self._sample_seconds = 0.0  # sampler self-cost, for the A/B lane
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name="metrics-history", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        self._thread = None

    def _run(self) -> None:
        while not self._stop_evt.wait(self.cadence):
            try:
                self.sample_once()
            except Exception:  # graftlint: disable=exception-hygiene -- the sampler must survive any plane's failure
                pass

    # -- collection ----------------------------------------------------------

    def _rate(self, key: str, cum: float, now: float) -> float:
        """Per-second delta of a cumulative counter; 0.0 on the first
        observation or a counter reset (restart)."""
        prev = self._prev.get(key)
        self._prev[key] = (float(cum), now)
        if prev is None:
            return 0.0
        pv, pt = prev
        if now <= pt or cum < pv:
            return 0.0
        return (float(cum) - pv) / (now - pt)

    def _collect(self) -> dict:
        """One curated gauge sample across the planes.  Cumulative
        counters become per-second rates so decimation-by-mean is
        meaningful for every series."""
        now = time.monotonic()
        s: dict[str, float] = {}
        slo = getattr(self.holder, "slo", None)
        if slo is not None:
            try:
                # series_sample, not snapshot(): the full objective
                # walk is exposition-grade work, too heavy per tick
                for cname, c in slo.series_sample().items():
                    base = f"slo.{cname}"
                    if c["p50Ms"] is not None:
                        s[f"{base}.p50_ms"] = c["p50Ms"]
                    if c["p99Ms"] is not None:
                        s[f"{base}.p99_ms"] = c["p99Ms"]
                    s[f"{base}.availability"] = c["availability"]
                    if "burnRate" in c:
                        s[f"{base}.burn"] = c["burnRate"]
                    s[f"{base}.rps"] = self._rate(
                        f"{base}.total", c["total"], now
                    )
                    s[f"{base}.eps"] = self._rate(
                        f"{base}.errors", c["errors"], now
                    )
            except Exception:  # graftlint: disable=exception-hygiene -- one plane failing must not starve the others
                pass
        api = self.api
        batcher = getattr(api, "batcher", None) if api is not None else None
        if batcher is not None:
            try:
                b = batcher.snapshot()
                s["batcher.depth"] = b["depth"]
                s["batcher.batches_ps"] = self._rate(
                    "batcher.batches", b["batches"], now
                )
                s["batcher.coalesced_ps"] = self._rate(
                    "batcher.coalesced", b["coalesced"], now
                )
            except Exception:  # graftlint: disable=exception-hygiene -- one plane failing must not starve the others
                pass
        qos = getattr(api, "qos", None) if api is not None else None
        if qos is not None:
            try:
                q = qos.snapshot()
                for tname, t in q["tenants"].items():
                    tb = f"qos.{tname}"
                    s[f"{tb}.admitted_ps"] = self._rate(
                        f"{tb}.admitted", t["admitted"], now
                    )
                    s[f"{tb}.shed_ps"] = self._rate(
                        f"{tb}.shed", t["shed"], now
                    )
                    s[f"{tb}.debt_ms"] = t["debtMs"]
            except Exception:  # graftlint: disable=exception-hygiene -- one plane failing must not starve the others
                pass
        try:
            from pilosa_tpu.obs import devledger

            c = devledger.counters()
            s["dev.device_ms_ps"] = self._rate(
                "dev.deviceMs", c["deviceMs"], now
            )
            s["dev.compiles_ps"] = self._rate(
                "dev.compiles", c["compiles"], now
            )
            s["dev.transfer_bytes_ps"] = self._rate(
                "dev.transferBytes", c["h2dBytes"] + c["d2hBytes"], now
            )
        except Exception:  # graftlint: disable=exception-hygiene -- one plane failing must not starve the others
            pass
        try:
            from pilosa_tpu.core import residency

            r = residency.default_tracker().snapshot()
            s["res.hits_ps"] = self._rate(
                "res.hits", r["deviceHits"], now
            )
            s["res.evictions_ps"] = self._rate(
                "res.evictions",
                r.get("autoUnpins", 0) + r.get("prefetchWasted", 0),
                now,
            )
            s["res.prefetch_ps"] = self._rate(
                "res.prefetch", r["prefetchIssued"], now
            )
        except Exception:  # graftlint: disable=exception-hygiene -- one plane failing must not starve the others
            pass
        ingest = getattr(api, "ingest", None) if api is not None else None
        if ingest is not None:
            try:
                snap = ingest.snapshot()
                s["ingest.decoded_ps"] = self._rate(
                    "ingest.decoded", snap["decoded"], now
                )
                pool = snap.get("pool") or {}
                for k in ("occupancy", "inUse", "used"):
                    if k in pool:
                        s["ingest.occupancy"] = pool[k]
                        break
                up = snap.get("uploader")
                if up is not None:
                    s["ingest.h2d_bytes_ps"] = self._rate(
                        "ingest.h2dBytes", up["h2dBytes"], now
                    )
            except Exception:  # graftlint: disable=exception-hygiene -- one plane failing must not starve the others
                pass
        return s

    def sample_once(self) -> None:
        t0 = time.monotonic()
        sample = self._collect()
        self.record(sample)
        stats = getattr(self.holder, "stats", None)
        if stats is not None:
            stats.count("history_samples")
        self._sample_seconds += time.monotonic() - t0

    # -- storage -------------------------------------------------------------

    def record(self, sample: dict, wall: float | None = None) -> None:
        """Append one sample to the base ring, fold completed decimation
        windows into coarser tiers, then run the trend detectors."""
        if wall is None:
            wall = time.time()
        with self._lock:
            base = self.tiers[0]
            base.append(wall, sample)
            self._samples_taken += 1
            for tier in self.tiers[1:]:
                d = tier.decimate
                if base.count % d != 0:
                    continue
                times, values = base.window(base.count - d)
                folded = {
                    name: _nanmean(win) for name, win in values.items()
                }
                folded = {
                    k: v for k, v in folded.items() if not np.isnan(v)
                }
                tier.append(float(times[-1]), folded)
        self._detect(sample, wall)

    # -- query ---------------------------------------------------------------

    def _pick_tier(self, step: float | None) -> _Tier:
        if step is None:
            return self.tiers[0]
        pick = self.tiers[0]
        for tier in self.tiers:
            if self.cadence * tier.decimate <= float(step) * (1 + 1e-9):
                pick = tier
        return pick

    @staticmethod
    def _match(name: str, patterns) -> bool:
        if not patterns:
            return True
        return any(fnmatch.fnmatchcase(name, p) for p in patterns)

    def query(
        self,
        series=None,
        since: int | None = None,
        step: float | None = None,
        limit: int | None = None,
    ) -> dict:
        """Windowed, optionally-downsampled read of the rings.

        ``series`` is a glob (or comma list / list of globs) over series
        names; ``since`` is a base-unit seq cursor (resume with the
        returned ``nextSeq``); ``step`` selects the coarsest tier not
        coarser than the requested resolution, then mean-downsamples the
        rest of the way; ``limit`` keeps only the newest N samples.
        Gap-honest: ``truncated`` is True when ``since`` predates the
        oldest retained sample in the serving tier."""
        if isinstance(series, str):
            series = [p.strip() for p in series.split(",") if p.strip()]
        with self._lock:
            tier = self._pick_tier(step)
            d = tier.decimate
            eff_step = self.cadence * d
            valid = min(tier.count, tier.capacity)
            start = tier.count - valid
            truncated = False
            if since is not None:
                want = -(-max(0, int(since)) // d)  # ceil division
                if want < start:
                    truncated = True
                start = max(start, min(want, tier.count))
            if limit is not None and limit >= 0:
                start = max(start, tier.count - int(limit))
            times, values = tier.window(start)
            names = sorted(
                n for n in values.keys() if self._match(n, series)
            )
            out_series = {}
            for name in names:
                vals = values[name]
                pts = [
                    [round(float(t), 3),
                     None if np.isnan(v) else float(v)]
                    for t, v in zip(times, vals)
                ]
                if step is not None and float(step) > 0:
                    # always downsample on an explicit step — even at
                    # step == tierStep it snaps raw sampler-phase times
                    # onto the floor(t/step)*step grid, which is what
                    # keeps a cluster merge wall-clock ALIGNED
                    pts = downsample(pts, float(step))
                out_series[name] = pts
            payload = {
                "node": self.node_id,
                "cadence": self.cadence,
                "step": float(step) if step is not None else eff_step,
                "tierStep": eff_step,
                "tiers": [
                    {
                        "step": self.cadence * t.decimate,
                        "capacity": t.capacity,
                        "retained": min(t.count, t.capacity),
                    }
                    for t in self.tiers
                ],
                "series": out_series,
                "seq": self.tiers[0].count,
                "nextSeq": tier.count * d,
                "firstSeq": (tier.count - valid) * d,
                "returned": int(tier.count - start),
                "truncated": truncated,
            }
        payload["detectors"] = self.trend_state()
        return payload

    # -- trend detection -----------------------------------------------------

    def _class_of(self, name: str, suffix: str) -> str:
        return name[len("slo."):len(name) - len(suffix)]

    def _detect(self, sample: dict, wall: float) -> None:
        fired_now: list[dict] = []
        with self._lock:
            for kind in ALL_DETECTORS:
                if kind not in self.detectors:
                    continue
                suffix, trig_name = _DETECTOR_SUFFIX[kind]
                for name, v in sample.items():
                    if not name.startswith("slo.") or not name.endswith(
                        suffix
                    ):
                        continue
                    t = self._step_detector(kind, name, float(v))
                    if t is not None:
                        t["at"] = round(wall, 3)
                        t["class"] = self._class_of(name, suffix)
                        t["detector"] = trig_name
                        fired_now.append(t)
            was_active = self._episode_active
            self._episode_active = any(
                st.latched for st in self._det.values()
            )
            # one trend episode = one incident: series tripping while
            # any detector is already latched join the episode silently
            if was_active:
                fired_now = []
            elif fired_now:
                fired_now = fired_now[:1]
                self._fired.extend(fired_now)
                del self._fired[:-_MAX_FIRED]
        for trigger in fired_now:
            self._fire(trigger)

    def _step_detector(
        self, kind: str, name: str, v: float
    ) -> dict | None:
        """Advance one (detector, series) state machine; returns a
        trigger skeleton on a fresh latch.  The baseline is FROZEN from
        the first breaching sample until the episode unlatches — an
        EWMA that chases the regression would declare it the new
        normal — and unlatching takes ``trips`` consecutive samples
        past the recovery midpoint, not merely under the latch line."""
        if np.isnan(v):
            return None
        st = self._det.get((kind, name))
        if st is None:
            st = self._det[(kind, name)] = _DetState()
        if kind == DETECTOR_THROUGHPUT and v <= 0.0:
            # idle != collapse: no offered load is indistinguishable
            # from zero goodput, so idle neither breaches nor feeds the
            # baseline; it does count toward re-arm so a latched
            # detector recovers when the burst ends.
            if st.latched:
                st.good += 1
                st.bad = 0
                if st.good >= self.trips:
                    st.latched = False
            return None
        if st.latched:
            # hysteresis: recovery must clear the MIDPOINT between the
            # baseline and the latch threshold, not merely dip under
            # the latch line — and the baseline stays frozen for the
            # whole episode.  Without both, a regression hovering near
            # the threshold drags the EWMA up on each "good" sample
            # until the episode unlatches and immediately re-fires.
            if kind == DETECTOR_LATENCY:
                recovered = v <= max(
                    st.mean * (1.0 + (self.latency_factor - 1.0) / 2.0),
                    st.mean + self.latency_min_ms / 2.0,
                )
            elif kind == DETECTOR_THROUGHPUT:
                recovered = v >= st.mean * min(
                    1.0, (1.0 + self.collapse_frac) / 2.0
                )
            else:
                recovered = v <= max(
                    st.mean * (1.0 + (self.error_factor - 1.0) / 2.0),
                    self.error_min_eps / 2.0,
                )
            if recovered:
                st.good += 1
                st.bad = 0
                if st.good >= self.trips:
                    st.latched = False
            else:
                st.good = 0
            return None
        breach = False
        if st.n >= self.warmup and st.mean is not None:
            if kind == DETECTOR_LATENCY:
                breach = v > max(
                    st.mean * self.latency_factor,
                    st.mean + self.latency_min_ms,
                )
            elif kind == DETECTOR_THROUGHPUT:
                breach = (
                    st.mean >= self.collapse_min_rps
                    and v < st.mean * self.collapse_frac
                )
            elif kind == DETECTOR_ERRORS:
                breach = v > max(
                    st.mean * self.error_factor, self.error_min_eps
                )
        if breach:
            st.bad += 1
            st.good = 0
        else:
            st.good += 1
            st.bad = 0
            if st.mean is None:
                st.mean = v
            else:
                st.mean += self.ewma_alpha * (v - st.mean)
            st.n += 1
        if st.bad >= self.trips:
            st.latched = True
            return {
                "type": "trend",
                "series": name,
                "baseline": round(st.mean, 4),
                "observed": round(v, 4),
                "samples": st.bad,
            }
        return None

    def _fire(self, trigger: dict) -> None:
        stats = getattr(self.holder, "stats", None)
        if stats is not None:
            stats.count("history_trend_incidents")
        fr = self.flightrec
        if fr is not None:
            fr.capture_incident(dict(trigger))

    # -- incident attachment / exposition ------------------------------------

    def incident_series(self, trigger: dict) -> dict | None:
        """Flight-recorder ``series_provider`` hook: the series windows
        to freeze into an incident bundle — the full retained base-tier
        window for the regressed class (or everything for non-trend
        triggers the caller scoped), plus the coarse tier so the bundle
        reaches back past the base ring (>= 60 s of pre-incident
        history at production cadence)."""
        cls = trigger.get("class")
        pats = [f"slo.{cls}.*"] if cls else None
        q = self.query(series=pats)
        out = {
            "cadence": self.cadence,
            "series": q["series"],
            "nextSeq": q["nextSeq"],
        }
        span = 0.0
        for pts in q["series"].values():
            if len(pts) >= 2:
                span = max(span, pts[-1][0] - pts[0][0])
        out["preSeconds"] = round(span, 3)
        if len(self.tiers) > 1:
            coarse_step = self.cadence * self.tiers[-1].decimate
            out["coarse"] = self.query(series=pats, step=coarse_step)[
                "series"
            ]
        return out

    def blackbox_snapshot(self, window_s: float = 60.0) -> dict:
        """Black-box checkpoint block: the trailing ``window_s`` of
        every base-tier series plus detector state — enough that a
        postmortem can answer "what did the last minute look like"
        without the rings that died with the process."""
        import math

        limit = max(1, int(math.ceil(float(window_s) / self.cadence)))
        q = self.query(limit=limit)
        return {
            "cadence": self.cadence,
            "windowSeconds": float(window_s),
            "series": q["series"],
            "nextSeq": q["nextSeq"],
            "detectors": q["detectors"],
            "stats": self.stats(),
        }

    def trend_state(self) -> dict:
        with self._lock:
            return {
                "enabled": sorted(self.detectors),
                "episodeActive": self._episode_active,
                "fired": list(self._fired),
                "series": {
                    f"{kind}:{name}": {
                        "baseline": (
                            round(st.mean, 4) if st.mean is not None
                            else None
                        ),
                        "n": st.n,
                        "latched": st.latched,
                    }
                    for (kind, name), st in sorted(self._det.items())
                },
            }

    def stats(self) -> dict:
        """Sampler self-accounting for /debug/vars and the bench lane."""
        with self._lock:
            return {
                "cadence": self.cadence,
                "samples": self._samples_taken,
                "series": len(self.tiers[0].values),
                "sampleSeconds": round(self._sample_seconds, 6),
                "trendFired": len(self._fired),
            }
