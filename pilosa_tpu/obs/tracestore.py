"""Per-node trace store with tail-based sampling (Dapper §4: keep the
traces that mattered — errors and tail-latency outliers — decided at
trace completion, not at trace start like head sampling).

Spans reach the store through :func:`feed`, installed as the tracing
module's span sink; which *store* a span lands in is carried by a
context variable activated per HTTP request (so multi-node in-process
test clusters route each node's spans to that node's own store — a
process-global store would merge them).

Retention is two-tier:

* ``_kept`` — traces that passed the tail policy (error, slow per the
  SLO latency objective for the request's op class, or a deterministic
  1-in-N baseline).  These are what ``GET /debug/traces`` lists and
  what metric exemplars point at.
* ``_recent`` — the spans of *every* recently completed trace,
  regardless of the local tail decision.  A coordinator assembling one
  trace cluster-wide (``?cluster=true``) asks every node for spans by
  trace id; the remote leg of a slow query is often itself fast, so the
  remote node would have dropped it from ``_kept`` — ``_recent`` is the
  short-lived memory that makes cross-node assembly work anyway.

The baseline decision hashes the trace id, so every node that touches a
trace makes the SAME keep/drop call — a baseline-kept trace is kept
whole across the cluster (Dapper's coherent-sampling property).
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from collections import OrderedDict

from pilosa_tpu.obs import tracing

# Fallback slow-keep threshold for spans with no op class or no latency
# objective (matches slo.DEFAULT_OBJECTIVES' read.other tier).
DEFAULT_SLOW_SECONDS = 0.250

_active_store: contextvars.ContextVar["TraceStore | None"] = (
    contextvars.ContextVar("pilosa_trace_store", default=None)
)


@contextlib.contextmanager
def activate(store: "TraceStore | None"):
    """Route spans finished inside this context into ``store``."""
    token = _active_store.set(store)
    try:
        yield store
    finally:
        _active_store.reset(token)


def feed(span) -> None:
    """tracing span sink: deliver one finished span to the active store."""
    store = _active_store.get()
    if store is not None:
        store.observe(span)


tracing.set_span_sink(feed)


def _span_dict(span, node_id: str) -> dict:
    # Rendered lazily at READ time (/debug/traces), never on the span
    # hot path: the store retains Span objects and pays the hex
    # formatting + tag copy only for traces somebody actually asks for.
    return {
        "traceId": f"{span.context.trace_id & (2**128 - 1):032x}",
        "spanId": f"{span.context.span_id & (2**64 - 1):016x}",
        "parentId": (
            f"{span.parent_id & (2**64 - 1):016x}" if span.parent_id else None
        ),
        "name": span.name,
        "node": node_id,
        "startUnixMs": span.start_unix_ns // 1_000_000,
        "durationMs": round((span.duration or 0.0) * 1e3, 3),
        "tags": {
            k: v for k, v in span.tags.items() if k != "logs"
        },
    }


def baseline_kept(trace_id: int, baseline_n: int) -> bool:
    """Deterministic 1-in-N keep from the trace id alone — the same
    verdict on every node (Fibonacci-hash mix, like ExportingTracer)."""
    if baseline_n <= 0:
        return False
    if baseline_n == 1:
        return True
    mixed = (trace_id * 0x9E3779B97F4A7C15) & (2**64 - 1)
    return mixed % baseline_n == 0


class TraceStore:
    """Bounded per-node store of completed traces (tail-sampled)."""

    def __init__(
        self,
        slo=None,
        capacity: int = 256,
        recent_capacity: int = 512,
        baseline_n: int = 128,
        pending_limit: int = 1024,
    ):
        self.slo = slo  # SLOTracker: latency objectives = slow thresholds
        self.node_id = ""
        self.capacity = max(1, int(capacity))
        self.recent_capacity = max(1, int(recent_capacity))
        self.baseline_n = int(baseline_n)
        self.pending_limit = max(16, int(pending_limit))
        # on_keep(op_class, seconds, trace_id_hex): exemplar hook —
        # the Holder wires this to the SLO tracker's histogram buckets.
        self.on_keep = None
        self._lock = threading.Lock()
        self._pending: OrderedDict[int, list] = OrderedDict()
        self._kept: OrderedDict[int, dict] = OrderedDict()
        self._recent: OrderedDict[int, list[dict]] = OrderedDict()
        self._stats = {"completed": 0, "kept": 0, "dropped": 0,
                       "kept_error": 0, "kept_slow": 0, "kept_baseline": 0,
                       "pending_evicted": 0}

    # -- ingest --------------------------------------------------------------

    def observe(self, span) -> None:
        """Called (via the span sink) for every finished span."""
        try:
            self._observe(span)
        except Exception:  # graftlint: disable=exception-hygiene -- observability must never fail the traced request
            pass

    def _observe(self, span) -> None:
        tid = span.context.trace_id
        with self._lock:
            self._pending.setdefault(tid, []).append(span)
            # bound the in-flight set: a span whose root never finishes
            # (crashed handler, dropped client) must not leak forever
            while len(self._pending) > self.pending_limit:
                self._pending.popitem(last=False)
                self._stats["pending_evicted"] += 1
            if not getattr(span, "local_root", False):
                return
            spans = self._pending.pop(tid, [span])
        self._complete(tid, span, spans)

    def _complete(self, tid: int, root, spans: list) -> None:
        duration = root.duration or 0.0
        op_class = root.tags.get("op_class")
        error = bool(root.tags.get("error"))
        reason = self._tail_reason(tid, op_class, duration, error)
        with self._lock:
            self._stats["completed"] += 1
            self._recent[tid] = spans
            while len(self._recent) > self.recent_capacity:
                self._recent.popitem(last=False)
            if reason is None:
                self._stats["dropped"] += 1
                return
            self._stats["kept"] += 1
            self._stats[f"kept_{reason}"] += 1
            self._kept[tid] = {
                "traceId": f"{tid & (2**128 - 1):032x}",
                "root": root.name,
                "opClass": op_class,
                "error": error,
                "durationMs": round(duration * 1e3, 3),
                "reason": reason,
                "at": time.time(),
                "spans": spans,
            }
            while len(self._kept) > self.capacity:
                self._kept.popitem(last=False)
        hook = self.on_keep
        if hook is not None and op_class:
            try:
                hook(op_class, duration, f"{tid & (2**128 - 1):032x}")
            except Exception:  # graftlint: disable=exception-hygiene -- exemplar wiring must not fail the request
                pass

    def _tail_reason(self, tid, op_class, duration, error) -> str | None:
        if error:
            return "error"
        if duration > self._slow_threshold(op_class):
            return "slow"
        if baseline_kept(tid, self.baseline_n):
            return "baseline"
        return None

    def _slow_threshold(self, op_class) -> float:
        slo = self.slo
        if slo is not None and op_class:
            obj = slo.objectives.get(op_class)
            if obj is not None and obj.latency_p99 is not None:
                return obj.latency_p99
        return DEFAULT_SLOW_SECONDS

    # -- queries -------------------------------------------------------------

    def kept_ids(self) -> set[str]:
        with self._lock:
            return {rec["traceId"] for rec in self._kept.values()}

    def last_kept_id(self) -> str | None:
        with self._lock:
            if not self._kept:
                return None
            return next(reversed(self._kept.values()))["traceId"]

    def summaries(self, limit: int = 100) -> list[dict]:
        """Newest-first kept-trace summaries (no span bodies)."""
        with self._lock:
            recs = list(self._kept.values())[-limit:]
        return [
            {k: v for k, v in rec.items() if k != "spans"}
            for rec in reversed(recs)
        ]

    def detail(self, trace_id_hex: str) -> dict | None:
        try:
            tid = int(trace_id_hex, 16)
        except (TypeError, ValueError):
            return None
        with self._lock:
            rec = self._kept.get(tid)
            if rec is None:
                return None
            out = {k: v for k, v in rec.items() if k != "spans"}
            spans = list(rec["spans"])
        out["spans"] = [_span_dict(s, self.node_id) for s in spans]
        return out

    def spans_for(self, trace_id_hex: str) -> list[dict]:
        """All spans this node holds for one trace — kept OR merely
        recent (the cross-node assembly path)."""
        try:
            tid = int(trace_id_hex, 16)
        except (TypeError, ValueError):
            return []
        with self._lock:
            rec = self._kept.get(tid)
            if rec is not None:
                spans = list(rec["spans"])
            else:
                spans = list(self._recent.get(tid, ()))
        return [_span_dict(s, self.node_id) for s in spans]

    def blackbox_snapshot(self, limit: int = 32) -> dict:
        """Black-box checkpoint block: kept-trace summaries (no span
        bodies — the spool is bounded) plus the store's counters."""
        return {
            "summaries": self.summaries(limit),
            "snapshot": self.snapshot(),
        }

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "node": self.node_id,
                "capacity": self.capacity,
                "baselineN": self.baseline_n,
                "kept": len(self._kept),
                "pending": len(self._pending),
                "stats": dict(self._stats),
            }
