"""Per-query profiling plane (``?profile=true``).

The reference Pilosa answers "where did my milliseconds go" with ~80
Jaeger spans; our TPU-native executor adds a dimension the Go lineage
never had — every call may run on one of three dispatch lanes (Pallas
kernel, XLA fallback, host op) with compile caches, serving caches and
host<->device transfers in between.  This module makes that attributable
to an individual query:

* a :class:`QueryProfile` collector carried in a ``contextvars.ContextVar``
  (the same ambient-context pattern as ``tracing._active_span``), so the
  executor, the kernels and the fan-out client all report into the query
  that is actually running — including across ``dist._submit`` worker
  threads, which copy the context;
* ``tracing.Span.__enter__/__exit__`` mirror every span into the profile
  tree, so per-PQL-call wall times and fan-out structure come for free
  from the existing instrumentation;
* ``ops/kernels.py`` appends per-kernel records (lane taken, demotions,
  compile-cache hit/miss, padded vs useful bytes, transfer bytes) via
  :func:`record_kernel`;
* remote nodes return their own ``QueryProfile.to_dict()`` in the
  fan-out response and the coordinator grafts it under the fan-out span
  via :func:`add_subprofile`, yielding one merged tree;
* :class:`SlowQueryLog` keeps full profiles of the worst recent queries
  for ``/debug/slow-queries`` (reference: the ``long-query-time`` log
  line, upgraded from a log line to a ring of call trees).

Everything here is stdlib-only so ``tracing`` can import it without
cycles, and every hook is a no-op costing one ContextVar read when no
profile is active.
"""

from __future__ import annotations

import contextvars
import threading
import time

# Bound the per-profile kernel-record count: a pathological query
# (k-level GroupBy over thousands of combos) must not balloon the
# response or the slow-query ring.
MAX_KERNEL_RECORDS = 256

_active: contextvars.ContextVar["QueryProfile | None"] = contextvars.ContextVar(
    "pilosa_query_profile", default=None
)
_current_node: contextvars.ContextVar["_PNode | None"] = contextvars.ContextVar(
    "pilosa_profile_node", default=None
)


class _PNode:
    """One node of the profile call tree (mirrors one tracing span)."""

    __slots__ = ("name", "tags", "duration_ms", "children", "kernels",
                 "stats", "subprofiles")

    def __init__(self, name: str):
        self.name = name
        self.tags: dict = {}
        self.duration_ms: float | None = None
        self.children: list[_PNode] = []
        self.kernels: list[dict] = []
        self.stats: dict[str, float] = {}
        self.subprofiles: list[dict] = []

    def to_dict(self) -> dict:
        d: dict = {"name": self.name, "duration_ms": self.duration_ms}
        if self.tags:
            d["tags"] = {k: v for k, v in self.tags.items() if k != "logs"}
        if self.stats:
            d["stats"] = dict(self.stats)
        if self.kernels:
            d["kernels"] = list(self.kernels)
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        if self.subprofiles:
            d["subprofiles"] = list(self.subprofiles)
        return d


class QueryProfile:
    """Collector for one query execution on one node.

    Tree mutation happens on the request thread and on fan-out pool
    threads (``dist._submit`` copies the context, so each worker's
    ``_current_node`` points at its own ``fanout`` child) — the lock
    guards the shared aggregates."""

    def __init__(self, index: str = "", query: str = "", node_id: str = ""):
        self.index = index
        self.query = query
        self.node_id = node_id
        self.started_at = time.time()
        self.duration_ms: float | None = None
        self.error: str | None = None
        self.root = _PNode("query")
        self._lock = threading.Lock()
        self._kernel_records = 0
        self._kernel_dropped = 0
        # ambient trace id (32-hex) captured at collection start: links
        # each slow-query-log entry to its /debug/traces record (lazy
        # import — tracing imports this module)
        from pilosa_tpu.obs import tracing

        span = tracing.active_span()
        self.trace_id: str | None = (
            f"{span.context.trace_id & (2**128 - 1):032x}"
            if span is not None
            else None
        )

    def finish(self, elapsed: float, error: str | None = None) -> None:
        self.duration_ms = elapsed * 1e3
        self.error = error

    def to_dict(self) -> dict:
        d = {
            "node": self.node_id,
            "index": self.index,
            "query": self.query,
            "startedAt": self.started_at,
            "duration_ms": self.duration_ms,
            "tree": self.root.to_dict(),
        }
        if self.trace_id is not None:
            d["traceId"] = self.trace_id
        if self.error is not None:
            d["error"] = self.error
        if self._kernel_dropped:
            d["kernelRecordsDropped"] = self._kernel_dropped
        return d


def profiling() -> bool:
    """True when a profile collector is active in this context."""
    return _active.get() is not None


def span_enter(name: str):
    """Open a profile tree node; returns an opaque handle for
    :func:`span_exit`, or ``None`` when no profile is active.  Called by
    ``tracing.Span.__enter__`` for every span regardless of tracer."""
    prof = _active.get()
    if prof is None:
        return None
    parent = _current_node.get() or prof.root
    node = _PNode(name)
    with prof._lock:
        parent.children.append(node)
    token = _current_node.set(node)
    return node, token, time.perf_counter()


def span_exit(handle, tags: dict | None = None) -> None:
    if handle is None:
        return
    node, token, t0 = handle
    node.duration_ms = (time.perf_counter() - t0) * 1e3
    if tags:
        node.tags.update(tags)
    _current_node.reset(token)


class span:
    """Profile-only span context manager for sites that are too hot or
    too fine-grained for a tracing span (fan-out legs, cache probes).
    Costs one ContextVar read when inactive."""

    __slots__ = ("_name", "_tags", "_handle")

    def __init__(self, name: str, **tags):
        self._name = name
        self._tags = tags
        self._handle = None

    def __enter__(self) -> "span":
        if _active.get() is not None:
            self._handle = span_enter(self._name)
        return self

    def __exit__(self, *exc) -> None:
        span_exit(self._handle, self._tags)
        self._handle = None


def record_kernel(**rec) -> None:
    """Append one kernel-dispatch record to the current tree node
    (called from ``ops/kernels.py`` on every instrumented dispatch)."""
    prof = _active.get()
    if prof is None:
        return
    node = _current_node.get() or prof.root
    with prof._lock:
        if prof._kernel_records >= MAX_KERNEL_RECORDS:
            prof._kernel_dropped += 1
            return
        prof._kernel_records += 1
        node.kernels.append(rec)


def annotate(name: str, duration_ms: float | None = None, **tags) -> None:
    """Append a pre-measured child span to the current profile node.

    For stages timed OUTSIDE the request's own context: the batcher's
    dispatcher thread measures queue wait and batch dispatch without an
    active profile, and the submitting thread records those numbers
    into its own profile after wake-up.  No-op without a profile."""
    prof = _active.get()
    if prof is None:
        return
    parent = _current_node.get() or prof.root
    node = _PNode(name)
    node.duration_ms = duration_ms
    if tags:
        node.tags.update(tags)
    with prof._lock:
        parent.children.append(node)


def incr(name: str, n: float = 1) -> None:
    """Bump a per-node counter (serving-cache hits and friends)."""
    prof = _active.get()
    if prof is None:
        return
    node = _current_node.get() or prof.root
    with prof._lock:
        node.stats[name] = node.stats.get(name, 0) + n


def add_subprofile(node_id: str, tree: dict | None) -> None:
    """Graft a remote node's profile dict under the current node (the
    coordinator's fan-out leg), producing the merged cluster tree."""
    prof = _active.get()
    if prof is None or not tree:
        return
    node = _current_node.get() or prof.root
    with prof._lock:
        node.subprofiles.append({"node": node_id, "profile": tree})


class activate:
    """Install ``profile`` as the ambient collector for a ``with`` block
    (no-op when ``profile`` is None)."""

    __slots__ = ("_profile", "_token", "_node_token")

    def __init__(self, profile: QueryProfile | None):
        self._profile = profile
        self._token = None
        self._node_token = None

    def __enter__(self) -> QueryProfile | None:
        if self._profile is not None:
            self._token = _active.set(self._profile)
            self._node_token = _current_node.set(self._profile.root)
        return self._profile

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            _current_node.reset(self._node_token)
            _active.reset(self._token)
            self._token = None
            self._node_token = None


class SlowQueryLog:
    """Bounded ring of the worst recent query profiles (reference
    ``long-query-time`` config; served at ``/debug/slow-queries``)."""

    def __init__(self, threshold: float = 0.0, capacity: int = 32):
        self.threshold = threshold
        self.capacity = capacity
        self._lock = threading.Lock()
        self._entries: list[dict] = []

    @property
    def enabled(self) -> bool:
        return self.threshold > 0.0

    def observe(self, profile: QueryProfile) -> None:
        if not self.enabled or profile.duration_ms is None:
            return
        if profile.duration_ms < self.threshold * 1e3:
            return
        entry = {
            "index": profile.index,
            "query": profile.query,
            "elapsed_ms": profile.duration_ms,
            "at": profile.started_at,
            "profile": profile.to_dict(),
        }
        with self._lock:
            self._entries.append(entry)
            if len(self._entries) > self.capacity:
                # keep the worst `capacity` of the recent window
                self._entries.sort(key=lambda e: -e["elapsed_ms"])
                del self._entries[self.capacity:]

    def snapshot(self) -> dict:
        with self._lock:
            worst = sorted(self._entries, key=lambda e: -e["elapsed_ms"])
            return {
                "threshold": self.threshold,
                "count": len(worst),
                "queries": worst,
            }

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
