"""Span exporter: OTLP/HTTP JSON (reference
tracing/opentracing/opentracing.go:31-76 — the Jaeger agent adapter;
OTLP is its modern equivalent and needs no vendor SDK).

Spans batch in a bounded queue and a background thread POSTs
``{"resourceSpans": [...]}`` to ``<endpoint>/v1/traces``.  Export is
strictly best-effort: a down collector drops batches, never blocks or
fails the serving path.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import urllib.request

_SERVICE = "pilosa-tpu"


def _otlp_span(span) -> dict:
    # The span records its wall-clock anchor once at start; deriving it
    # here from time.time_ns() would skew every batched span by however
    # long it sat in the export queue.
    start_ns = getattr(span, "start_unix_ns", None)
    if start_ns is None:  # foreign span object without the anchor
        start_ns = int(time.time_ns() - (time.monotonic() - span.start) * 1e9)
    dur_ns = int((span.duration or 0.0) * 1e9)
    # OTLP status from the error tag the HTTP layer stamps before
    # finish: 2 = STATUS_CODE_ERROR, 0 = STATUS_CODE_UNSET
    status = {"code": 2} if span.tags.get("error") else {"code": 0}
    return {
        "status": status,
        "traceId": f"{span.context.trace_id & (2**128 - 1):032x}",
        "spanId": f"{span.context.span_id & (2**64 - 1):016x}",
        "parentSpanId": (
            f"{span.parent_id:016x}" if span.parent_id else ""
        ),
        "name": span.name,
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(start_ns),
        "endTimeUnixNano": str(start_ns + dur_ns),
        "attributes": [
            {
                "key": str(k),
                "value": {"stringValue": str(v)},
            }
            for k, v in span.tags.items()
            if k != "logs"
        ],
    }


class OTLPSpanExporter:
    def __init__(
        self,
        endpoint: str,
        batch_size: int = 64,
        flush_interval: float = 2.0,
        timeout: float = 5.0,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.batch_size = batch_size
        self.flush_interval = flush_interval
        self.timeout = timeout
        self._q: "queue.Queue" = queue.Queue(maxsize=4096)
        self._stop = threading.Event()
        self.exported = 0
        self.dropped = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def export(self, span) -> None:
        try:
            self._q.put_nowait(_otlp_span(span))
        except queue.Full:
            self.dropped += 1

    def _run(self) -> None:
        batch: list[dict] = []
        last = time.monotonic()
        while not self._stop.is_set():
            timeout = max(0.05, self.flush_interval - (time.monotonic() - last))
            try:
                batch.append(self._q.get(timeout=timeout))
            except queue.Empty:
                pass
            if batch and (
                len(batch) >= self.batch_size
                or time.monotonic() - last >= self.flush_interval
            ):
                self._post(batch)
                batch = []
                last = time.monotonic()
        if batch:
            self._post(batch)

    def _post(self, batch: list[dict]) -> None:
        body = json.dumps(
            {
                "resourceSpans": [
                    {
                        "resource": {
                            "attributes": [
                                {
                                    "key": "service.name",
                                    "value": {"stringValue": _SERVICE},
                                }
                            ]
                        },
                        "scopeSpans": [
                            {
                                "scope": {"name": _SERVICE},
                                "spans": batch,
                            }
                        ],
                    }
                ]
            }
        ).encode()
        req = urllib.request.Request(
            self.endpoint + "/v1/traces",
            data=body,
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout):
                self.exported += len(batch)
        except Exception:
            self.dropped += len(batch)

    def flush(self, deadline: float = 5.0) -> None:
        """Best-effort wait for the queue to drain (tests)."""
        t0 = time.monotonic()
        while not self._q.empty() and time.monotonic() - t0 < deadline:
            time.sleep(0.02)
        # one more interval so the in-flight batch posts
        time.sleep(min(self.flush_interval + 0.1, deadline))

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
