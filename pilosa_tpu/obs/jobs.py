"""Background-job progress tracking for long-running control-plane work.

Anti-entropy rounds, resize migrations, and import-pool drains can run
for minutes; the reference reports them only as log lines after the
fact.  The JobTracker gives each one a live record — phase, progress
counters (``fragments_done``/``fragments_total``, ``bytes_moved``),
derived rates and ETA, and a terminal status (``done``/``aborted``/
``error``) — served at ``/debug/jobs`` and mirrored into ``/metrics``
as ``pilosa_job_*`` series.

Progress counters come in ``<name>_done`` / ``<name>_total`` pairs;
when both exist the snapshot derives percentage, rate (done per
second over the job's lifetime), and ETA.  Bare counters (``bytes``)
just report a rate.
"""

from __future__ import annotations

import threading
import time
from collections import deque

STATUS_RUNNING = "running"
STATUS_DONE = "done"
STATUS_ABORTED = "aborted"
STATUS_ERROR = "error"

_TERMINAL = (STATUS_DONE, STATUS_ABORTED, STATUS_ERROR)


class Job:
    """One unit of tracked background work.  All mutators are
    thread-safe and monotonic: counters only advance, and a terminal
    status is final (later ``finish`` calls are ignored)."""

    def __init__(self, tracker: "JobTracker", job_id: int, kind: str,
                 node: str = "", **meta):
        self._tracker = tracker
        self._lock = threading.Lock()
        self.id = job_id
        self.kind = kind
        self.node = node
        self.meta = dict(meta)
        self.phase = ""
        self.status = STATUS_RUNNING
        self.error: str | None = None
        self.started = time.time()
        self.updated = self.started
        self.finished: float | None = None
        self._progress: dict[str, float] = {}

    # -- mutators ------------------------------------------------------------

    def set_phase(self, phase: str) -> None:
        with self._lock:
            if self.status == STATUS_RUNNING:
                self.phase = phase
                self.updated = time.time()

    def annotate(self, **meta) -> None:
        """Merge keys into the job's meta mid-flight (e.g. op-log
        catch-up lag per migration round) — meta is for labels that
        aren't monotonic counters, which is what progress is for."""
        with self._lock:
            if self.status == STATUS_RUNNING:
                self.meta.update(meta)
                self.updated = time.time()

    def advance(self, **counters: float) -> None:
        """Increment progress counters, e.g. ``advance(fragments_done=1,
        bytes=4096)``.  Counters never go backwards."""
        with self._lock:
            if self.status != STATUS_RUNNING:
                return
            for name, delta in counters.items():
                if delta > 0:
                    self._progress[name] = self._progress.get(name, 0) + delta
            self.updated = time.time()

    def set_progress(self, **counters: float) -> None:
        """Set absolute counter values (used for ``*_total`` targets).
        Values are clamped monotonic — a late, smaller total cannot make
        an observer's progress run backwards."""
        with self._lock:
            if self.status != STATUS_RUNNING:
                return
            for name, value in counters.items():
                if value >= self._progress.get(name, 0):
                    self._progress[name] = value
            self.updated = time.time()

    def finish(self, status: str = STATUS_DONE, error: str | None = None) -> None:
        with self._lock:
            if self.status != STATUS_RUNNING:
                return  # terminal is final
            self.status = status if status in _TERMINAL else STATUS_ERROR
            self.error = error
            self.finished = self.updated = time.time()
        self._tracker._on_finish(self)

    # -- views ---------------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            now = self.finished if self.finished is not None else time.time()
            elapsed = max(now - self.started, 1e-9)
            progress = dict(self._progress)
            out = {
                "id": self.id,
                "kind": self.kind,
                "node": self.node,
                "phase": self.phase,
                "status": self.status,
                "error": self.error,
                "started": self.started,
                "updated": self.updated,
                "finished": self.finished,
                "elapsed": now - self.started,
                "progress": progress,
                "meta": dict(self.meta),
            }
        rates: dict[str, float] = {}
        for name, value in progress.items():
            if name.endswith("_total"):
                continue
            rates[name + "_per_sec"] = value / elapsed
        out["rates"] = rates
        # Derive percent/ETA from the first *_done/*_total pair.
        for name, done in progress.items():
            if not name.endswith("_done"):
                continue
            total = progress.get(name[: -len("_done")] + "_total")
            if not total:
                continue
            out["percent"] = min(100.0, 100.0 * done / total)
            rate = done / elapsed
            if out["status"] == STATUS_RUNNING and rate > 0 and done < total:
                out["eta_seconds"] = (total - done) / rate
            break
        return out


class JobTracker:
    """Registry of active jobs plus a bounded history of finished ones.

    Mirrors lifecycle counts into the node's StatsClient when one is
    attached (``set_stats``): ``job_started{kind}``,
    ``job_finished{kind,status}`` counters and a ``job_active`` gauge —
    rendered by prometheus_text as ``pilosa_job_*`` series.
    """

    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        self._next_id = 0
        self._active: dict[int, Job] = {}
        self._history: deque[Job] = deque(maxlen=max(1, int(capacity)))
        self.stats = None  # StatsClient, attached by Holder.set_stats
        self.node_id = ""

    def start(self, kind: str, **meta) -> Job:
        with self._lock:
            self._next_id += 1
            job = Job(self, self._next_id, kind, node=self.node_id, **meta)
            self._active[job.id] = job
            active = len(self._active)
        stats = self.stats
        if stats is not None:
            stats.count_with_tags("job_started", 1, 1.0, [f"kind:{kind}"])
            stats.gauge("job_active", active)
        return job

    def _on_finish(self, job: Job) -> None:
        with self._lock:
            self._active.pop(job.id, None)
            self._history.append(job)
            active = len(self._active)
        stats = self.stats
        if stats is not None:
            stats.count_with_tags(
                "job_finished", 1, 1.0,
                [f"kind:{job.kind}", f"status:{job.status}"],
            )
            stats.gauge("job_active", active)

    def snapshot(self, kind: str | None = None) -> dict:
        """Active jobs plus finished history, newest first."""
        with self._lock:
            active = list(self._active.values())
            history = list(self._history)
        jobs = [j.snapshot() for j in active] + [
            j.snapshot() for j in reversed(history)
        ]
        if kind is not None:
            jobs = [j for j in jobs if j["kind"] == kind]
        jobs.sort(key=lambda j: j["id"], reverse=True)
        return {
            "active": sum(1 for j in jobs if j["status"] == STATUS_RUNNING),
            "jobs": jobs,
        }
