"""Version shims for the JAX APIs this project sits on.

``shard_map`` moved from ``jax.experimental.shard_map`` to the ``jax``
top level, and its replication-check kwarg was renamed ``check_rep`` ->
``check_vma`` along the way.  Kernel code writes the modern spelling
(``from pilosa_tpu.compat import shard_map`` + ``check_vma=``) and this
wrapper translates for older runtimes.
"""

from __future__ import annotations

try:  # modern jax: top-level export, check_vma kwarg
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # jax 0.4.x: experimental home, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    kwargs = {_CHECK_KW: check_vma}
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
