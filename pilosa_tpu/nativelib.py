"""Shared build-on-demand loader for the native C++ libraries.

Both native components (the roaring codec, storage/_native.py, and the
host latency-tier kernels, ops/_hostops.py) follow the same contract:
the .so is compiled next to its source with g++ on first use (so
``-march=native`` is always safe — the binary never leaves the machine
that built it), staleness is judged by source mtime, every entry point
degrades to a Python fallback when no toolchain exists, and
``PILOSA_TPU_NO_NATIVE=1`` forces the fallback.  One loader owns that
sequence so fixes (like the concurrent-build race below) cannot drift
between copies.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Callable


def build(src: str, lib_path: str) -> bool:
    """Compile ``src`` into ``lib_path`` atomically.

    The object is written to a PER-PROCESS temp name and os.replace'd
    in: two processes building concurrently (cluster nodes on one host,
    parallel test workers) each produce a complete .so and the last
    rename wins — a shared fixed temp name would interleave their
    compiler output into a permanently corrupt library.
    ``-march=native`` first (popcnt/AVX on x86); plain -O3 for
    toolchains that reject it."""
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(lib_path) or ".", suffix=".so.tmp"
    )
    os.close(fd)
    try:
        for extra in (["-march=native"], []):
            cmd = [
                "g++", "-O3", "-std=c++17", "-shared", "-fPIC", *extra,
                src, "-o", tmp,
            ]
            try:
                subprocess.run(
                    cmd, check=True, capture_output=True, timeout=120
                )
                os.replace(tmp, lib_path)
                return True
            except (OSError, subprocess.SubprocessError):
                continue
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def load(src: str, lib_path: str, bind: Callable[[ctypes.CDLL], None]):
    """Load (building if missing/stale) and bind the library; None when
    unavailable for any reason — toolchain absent, load failure, or a
    stale prebuilt .so missing expected symbols (``bind`` raising
    AttributeError).  Callers cache the result under their own lock."""
    if os.environ.get("PILOSA_TPU_NO_NATIVE"):
        return None
    if not os.path.exists(lib_path) or (
        os.path.exists(src)
        and os.path.getmtime(src) > os.path.getmtime(lib_path)
    ):
        if not os.path.exists(src) or not build(src, lib_path):
            return None
    try:
        lib = ctypes.CDLL(lib_path)
    except OSError:
        return None
    try:
        bind(lib)
    except AttributeError:
        return None
    return lib
