"""Backend-selection helper.

Some hosts register an accelerator backend from ``sitecustomize`` and pin
``jax.config.jax_platforms`` programmatically, which silently overrides
the ``JAX_PLATFORMS`` environment variable — so a user asking for the
CPU backend (tests, offline demos, CI) can end up initializing a TPU
tunnel that may hang.  Entry points call :func:`honor_platform_env`
before first device use to re-assert the user's explicit choice; when
the env var is unset the host's pin stands.
"""

from __future__ import annotations

import os


def honor_platform_env() -> None:
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    if jax.config.jax_platforms != want:
        jax.config.update("jax_platforms", want)
