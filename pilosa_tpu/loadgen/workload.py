"""Deterministic workload generation for the SLO load harness.

The generator pre-computes the ENTIRE request sequence from a seed
before any request is sent: op kinds from configurable mix weights,
row/column popularity from a zipfian sampler (the YCSB access-skew
model — a few hot keys take most of the traffic, the "millions of
users" shape), timestamps from a fixed base instant.  Two generators
built from the same config emit byte-identical sequences
(:func:`fingerprint` proves it), which is what makes an SLO_r*.json
report reproducible and diffable across code changes.

Op kinds map onto the server's SLO op classes (pilosa_tpu/obs/slo.py):

    count / row / topn / range_time / groupby  -> read.*
    range_bsi                                  -> read.range, int-field
                                                  predicates that ride
                                                  the query-batched BSI
                                                  lane
    set / set_tq / set_val                     -> write
    key_set / key_count                        -> write / read.count,
                                                  via string keys (the
                                                  translation hot path)
    translate                                  -> translate
    import_batch                               -> import
"""

from __future__ import annotations

import hashlib
import json

import numpy as np

# Fixed time base for time-quantum ops: generation must not read the
# wall clock (determinism), and 2026-01 spans month/day view edges.
TIME_BASE_YEAR = 2026
TIME_BASE_MONTH = 1
N_TQ_DAYS = 28
N_TQ_HOURS = N_TQ_DAYS * 24

# Int (BSI) field driven by range_bsi/set_val: bounds sized so the
# depth matches a realistic metric column and predicates land in-band.
BSI_FIELD = "val"
BSI_VAL_MIN = -4096
BSI_VAL_MAX = 4096

DEFAULT_MIX: dict[str, float] = {
    "count": 22.0,
    "row": 8.0,
    "topn": 6.0,
    "range_time": 8.0,
    "range_bsi": 6.0,
    "groupby": 4.0,
    "set": 12.0,
    "set_val": 4.0,
    "set_tq": 12.0,
    "key_set": 8.0,
    "key_count": 8.0,
    "translate": 6.0,
    "import_batch": 2.0,
}

# Expected server-side SLO class per op kind (report verdicts join on
# these).
OP_CLASS: dict[str, str] = {
    "count": "read.count",
    "row": "read.row",
    "topn": "read.topn",
    "range_time": "read.range",
    "range_bsi": "read.range",
    "groupby": "read.groupby",
    "set": "write",
    "set_tq": "write",
    "set_val": "write",
    "key_set": "write",
    "key_count": "read.count",
    "translate": "translate",
    "import_batch": "import",
}


class WorkloadConfig:
    """Seeded workload shape.  ``index`` is the unkeyed segmentation
    index (fields ``seg`` set + ``ev`` time-quantum); ``keys_index`` is
    the keyed index (field ``tag``, row+column keys) that puts string
    translation on the hot path."""

    def __init__(
        self,
        seed: int = 42,
        index: str = "slo_bench",
        keys_index: str = "slo_keys",
        n_rows: int = 32,
        n_cols: int = 50_000,
        n_user_keys: int = 2_000,
        zipf_theta: float = 0.99,
        import_batch_size: int = 256,
        mix: dict[str, float] | None = None,
    ):
        self.seed = int(seed)
        self.index = index
        self.keys_index = keys_index
        self.n_rows = int(n_rows)
        self.n_cols = int(n_cols)
        self.n_user_keys = int(n_user_keys)
        self.zipf_theta = float(zipf_theta)
        self.import_batch_size = int(import_batch_size)
        self.mix = dict(DEFAULT_MIX if mix is None else mix)
        unknown = set(self.mix) - set(OP_CLASS)
        if unknown:
            raise ValueError(f"unknown op kinds in mix: {sorted(unknown)}")

    def to_dict(self) -> dict:
        return {
            "seed": self.seed,
            "index": self.index,
            "keysIndex": self.keys_index,
            "nRows": self.n_rows,
            "nCols": self.n_cols,
            "nUserKeys": self.n_user_keys,
            "zipfTheta": self.zipf_theta,
            "importBatchSize": self.import_batch_size,
            "mix": self.mix,
        }


class Zipf:
    """Seedless zipfian rank sampler over ``[0, n)``: rank r drawn with
    probability ∝ 1/(r+1)^theta via inverse-CDF lookup.  The caller
    owns the rng so one generator stream drives every sampler
    (determinism is a property of the whole sequence, not each
    sampler)."""

    def __init__(self, n: int, theta: float):
        ranks = np.arange(1, n + 1, dtype=np.float64)
        w = ranks**-theta
        cdf = np.cumsum(w)
        self._cdf = cdf / cdf[-1]

    def sample(self, rng: np.random.Generator) -> int:
        return int(np.searchsorted(self._cdf, rng.random(), side="right"))


class Op:
    """One generated request: kind + the HTTP request to issue."""

    __slots__ = ("kind", "op_class", "method", "path", "body", "ctype")

    def __init__(self, kind: str, method: str, path: str, body: bytes, ctype: str):
        self.kind = kind
        self.op_class = OP_CLASS[kind]
        self.method = method
        self.path = path
        self.body = body
        self.ctype = ctype

    def to_wire(self) -> dict:
        return {
            "kind": self.kind,
            "method": self.method,
            "path": self.path,
            "body": self.body.decode("utf-8", "replace"),
        }


def fingerprint(ops: list[Op]) -> str:
    """sha256 over the canonical serialization of the full sequence —
    two same-seed runs must produce the same value."""
    h = hashlib.sha256()
    for op in ops:
        h.update(op.method.encode())
        h.update(op.path.encode())
        h.update(op.body)
        h.update(b"\x00")
    return h.hexdigest()


class WorkloadGenerator:
    """Pre-computes deterministic op sequences from the config seed.
    Each :meth:`sequence` call advances the generator's single rng
    stream, so consecutive stage sequences are distinct but the overall
    run replays exactly from the seed."""

    def __init__(self, config: WorkloadConfig):
        self.config = config
        self._rng = np.random.default_rng(config.seed)
        self._row_zipf = Zipf(config.n_rows, config.zipf_theta)
        self._col_zipf = Zipf(config.n_cols, config.zipf_theta)
        self._key_zipf = Zipf(config.n_user_keys, config.zipf_theta)

    # -- op builders ---------------------------------------------------

    def _ts(self, hour: int) -> str:
        day, h = divmod(hour, 24)
        return (
            f"{TIME_BASE_YEAR}-{TIME_BASE_MONTH:02d}-"
            f"{day + 1:02d}T{h:02d}:00"
        )

    def _query_op(self, kind: str, index: str, pql: str) -> Op:
        return Op(
            kind, "POST", f"/index/{index}/query", pql.encode(), "text/plain"
        )

    def _build(self, kind: str) -> Op:
        cfg = self.config
        rng = self._rng
        if kind == "count":
            r = self._row_zipf.sample(rng)
            if rng.random() < 0.3:
                r2 = self._row_zipf.sample(rng)
                return self._query_op(
                    kind, cfg.index,
                    f"Count(Intersect(Row(seg={r}), Row(seg={r2})))",
                )
            return self._query_op(kind, cfg.index, f"Count(Row(seg={r}))")
        if kind == "row":
            r = self._row_zipf.sample(rng)
            return self._query_op(kind, cfg.index, f"Row(seg={r})")
        if kind == "topn":
            return self._query_op(kind, cfg.index, "TopN(seg, n=5)")
        if kind == "range_time":
            r = self._row_zipf.sample(rng)
            d1 = int(rng.integers(0, N_TQ_DAYS - 1))
            span = int(rng.integers(1, 4))
            d2 = min(d1 + span, N_TQ_DAYS - 1)
            return self._query_op(
                kind, cfg.index,
                f"Range(ev={r}, {self._ts(d1 * 24)}, {self._ts(d2 * 24)})",
            )
        if kind == "range_bsi":
            # Top-level Range() so obs/slo.py classifies it read.range;
            # concurrent emitters coalesce into one query-batched BSI
            # flight server-side (ops/bsi.py range_count_batch and kin).
            b = int(rng.integers(BSI_VAL_MIN, BSI_VAL_MAX))
            shape = rng.random()
            if shape < 0.4:
                pql = f"Range({BSI_FIELD} < {b})"
            elif shape < 0.8:
                pql = f"Range({BSI_FIELD} > {b})"
            else:
                span = int(rng.integers(1, (BSI_VAL_MAX - BSI_VAL_MIN) // 8))
                pql = (
                    f"Range({BSI_FIELD} >< "
                    f"[{b}, {min(b + span, BSI_VAL_MAX)}])"
                )
            return self._query_op(kind, cfg.index, pql)
        if kind == "groupby":
            return self._query_op(kind, cfg.index, "GroupBy(Rows(seg), limit=8)")
        if kind == "set":
            r = self._row_zipf.sample(rng)
            c = self._col_zipf.sample(rng)
            return self._query_op(kind, cfg.index, f"Set({c}, seg={r})")
        if kind == "set_tq":
            r = self._row_zipf.sample(rng)
            c = self._col_zipf.sample(rng)
            hour = int(rng.integers(0, N_TQ_HOURS))
            return self._query_op(
                kind, cfg.index, f"Set({c}, ev={r}, {self._ts(hour)})"
            )
        if kind == "set_val":
            c = self._col_zipf.sample(rng)
            v = int(rng.integers(BSI_VAL_MIN, BSI_VAL_MAX))
            return self._query_op(
                kind, cfg.index, f"Set({c}, {BSI_FIELD}={v})"
            )
        if kind == "key_set":
            k = self._key_zipf.sample(rng)
            r = self._row_zipf.sample(rng)
            return self._query_op(
                kind, cfg.keys_index, f'Set("user{k}", tag="t{r}")'
            )
        if kind == "key_count":
            r = self._row_zipf.sample(rng)
            return self._query_op(
                kind, cfg.keys_index, f'Count(Row(tag="t{r}"))'
            )
        if kind == "translate":
            ks = sorted({self._key_zipf.sample(rng) for _ in range(8)})
            body = json.dumps(
                {
                    "index": cfg.keys_index,
                    "field": "",
                    "keys": [f"user{k}" for k in ks],
                }
            ).encode()
            return Op(
                kind, "POST", "/internal/translate/keys", body,
                "application/json",
            )
        if kind == "import_batch":
            n = cfg.import_batch_size
            rows = [self._row_zipf.sample(rng) for _ in range(n)]
            cols = [self._col_zipf.sample(rng) for _ in range(n)]
            body = json.dumps({"rowIDs": rows, "columnIDs": cols}).encode()
            return Op(
                kind, "POST", f"/index/{cfg.index}/field/seg/import", body,
                "application/json",
            )
        raise ValueError(f"unknown op kind: {kind}")

    # -- sequence ------------------------------------------------------

    def sequence(self, n: int, mix: dict[str, float] | None = None) -> list[Op]:
        """The next ``n`` ops of this generator's stream, kinds drawn
        from ``mix`` (default: the config mix)."""
        weights = dict(self.config.mix if mix is None else mix)
        kinds = sorted(weights)
        p = np.array([weights[k] for k in kinds], dtype=np.float64)
        if p.sum() <= 0:
            raise ValueError("mix weights must sum > 0")
        p /= p.sum()
        choices = self._rng.choice(len(kinds), size=n, p=p)
        return [self._build(kinds[i]) for i in choices]

    def sequence_repeat(
        self,
        n: int,
        mix: dict[str, float] | None = None,
        pool_size: int = 16,
        pool_theta: float | None = None,
    ) -> list[Op]:
        """The next ``n`` ops with a repeat-heavy read side: reads are
        drawn ZIPFIAN FROM A SMALL POOL of ``pool_size`` pre-built query
        templates instead of being freshly randomized, so the same exact
        queries recur the way dashboard refreshes do — the traffic shape
        the semantic result cache (docs/caching.md) exists for.  Writes
        (and every non-read kind) still randomize per-op from the mix,
        so cache entries face live invalidation pressure.  Deterministic
        like :meth:`sequence`: one rng stream drives the pool build, the
        kind draws, and the pool picks."""
        weights = dict(self.config.mix if mix is None else mix)
        read_weights = {
            k: w
            for k, w in weights.items()
            if OP_CLASS[k].startswith("read.") and w > 0
        }
        if not read_weights:
            return self.sequence(n, mix)
        # pool build advances the same stream (replays from the seed)
        pool = self.sequence(max(1, int(pool_size)), read_weights)
        pool_zipf = Zipf(
            len(pool),
            self.config.zipf_theta if pool_theta is None else pool_theta,
        )
        kinds = sorted(weights)
        p = np.array([weights[k] for k in kinds], dtype=np.float64)
        if p.sum() <= 0:
            raise ValueError("mix weights must sum > 0")
        p /= p.sum()
        choices = self._rng.choice(len(kinds), size=n, p=p)
        out: list[Op] = []
        for i in choices:
            kind = kinds[i]
            if kind in read_weights:
                out.append(pool[pool_zipf.sample(self._rng)])
            else:
                out.append(self._build(kind))
        return out

    def sequence_shared(
        self,
        n: int,
        mix: dict[str, float] | None = None,
        pool_size: int = 8,
        pool_theta: float | None = None,
    ) -> list[Op]:
        """The next ``n`` ops with reads replaced by shared-subtree
        FLIGHTS: each read op is one multi-call query whose calls embed
        a common canonical subtree (one occurrence commutatively
        flipped), so calls landing in one server-side batch group are
        the flight planner's CSE shape (docs/serving.md "Flight
        planning").  The shared subtrees carry a BSI condition, keeping
        them off the compiled count path — the dashboard burst where
        planning pays.  Flights are drawn zipfian from ``pool_size``
        pre-built templates; writes still randomize from the mix.
        Deterministic like :meth:`sequence`."""
        weights = dict(self.config.mix if mix is None else mix)
        read_weights = {
            k: w
            for k, w in weights.items()
            if OP_CLASS[k].startswith("read.") and w > 0
        }
        if not read_weights:
            return self.sequence(n, mix)
        rng = self._rng
        pool: list[Op] = []
        for _ in range(max(1, int(pool_size))):
            r = self._row_zipf.sample(rng)
            r2 = self._row_zipf.sample(rng)
            b = int(rng.integers(BSI_VAL_MIN, BSI_VAL_MAX))
            shared = f"Intersect(Row({BSI_FIELD} > {b}), Row(seg={r}))"
            # same canonical form, different child order
            flipped = f"Intersect(Row(seg={r}), Row({BSI_FIELD} > {b}))"
            # 4 of 6 calls consume the shared subtree (>= 50% per flight)
            flight = " ".join(
                [
                    f"Count({shared})",
                    f"Count(Union({flipped}, Row(seg={r2})))",
                    f"Count(Difference({shared}, Row(seg={r2})))",
                    f"Count(Intersect({shared}, Row(seg={r2})))",
                    f"Count(Row(seg={r2}))",
                    f"Count(Row(seg={r}))",
                ]
            )
            pool.append(self._query_op("count", self.config.index, flight))
        pool_zipf = Zipf(
            len(pool),
            self.config.zipf_theta if pool_theta is None else pool_theta,
        )
        kinds = sorted(weights)
        p = np.array([weights[k] for k in kinds], dtype=np.float64)
        if p.sum() <= 0:
            raise ValueError("mix weights must sum > 0")
        p /= p.sum()
        choices = self._rng.choice(len(kinds), size=n, p=p)
        out: list[Op] = []
        for i in choices:
            kind = kinds[i]
            if kind in read_weights:
                out.append(pool[pool_zipf.sample(rng)])
            else:
                out.append(self._build(kind))
        return out


def schema_ops(config: WorkloadConfig) -> list[tuple[str, str, dict]]:
    """Schema the workload needs, as (kind, name, options) steps the
    harness applies through the API before driving load."""
    return [
        ("index", config.index, {}),
        ("field", f"{config.index}/seg", {}),
        ("field", f"{config.index}/ev", {"type": "time", "timeQuantum": "YMD"}),
        (
            "field",
            f"{config.index}/{BSI_FIELD}",
            {"type": "int", "min": BSI_VAL_MIN, "max": BSI_VAL_MAX},
        ),
        ("index", config.keys_index, {"keys": True}),
        ("field", f"{config.keys_index}/tag", {"keys": True}),
    ]
