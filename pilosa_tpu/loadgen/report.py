"""SLO report: the machine-readable artifact one harness run emits.

``SLO_r*.json`` sits next to ``BENCH_*.json`` and makes the north-star
("serve heavy mixed traffic inside objectives") a regressable number:
per-op-class client-side p50/p99/p999, error-budget burn from the
server's own tracker, and a pass/fail verdict per objective-bearing
class.  ``validate_report`` is the schema contract the smoke test and
CI assert against.
"""

from __future__ import annotations

import math
import os

SCHEMA = "pilosa-slo-report/v1"


def _percentile(sorted_vals: list[float], q: float) -> float | None:
    if not sorted_vals:
        return None
    idx = max(0, min(len(sorted_vals) - 1, math.ceil(q * len(sorted_vals)) - 1))
    return sorted_vals[idx]


def build_report(
    config: dict,
    stages: list[dict],
    records: list[tuple[str, float, float, bool, int, str | None]],
    client_errors: int,
    wall_seconds: float,
    sequence_fingerprint: str,
    server_slo: dict | None,
    live_slo_ok: bool,
    slo_metrics_present: bool,
    incidents: dict | None = None,
    events: dict | None = None,
    residency: dict | None = None,
    rescache: dict | None = None,
    planner: dict | None = None,
    devcosts: dict | None = None,
    qos: dict | None = None,
    history: dict | None = None,
) -> dict:
    """Aggregate worker records + the server's SLO snapshot into the
    report dict.  ``records`` rows are (op_class, open_loop_latency_s,
    service_latency_s, ok, http_status, tenant)."""
    by_class: dict[str, dict] = {}
    by_tenant: dict[str, dict] = {}
    for op_class, lat, svc, ok, status, tenant in records:
        c = by_class.setdefault(
            op_class,
            {"count": 0, "errors": 0, "lat": [], "svc": []},
        )
        c["count"] += 1
        if not ok:
            c["errors"] += 1
        c["lat"].append(lat)
        c["svc"].append(svc)
        if tenant:
            t = by_tenant.setdefault(
                tenant,
                {"count": 0, "errors": 0, "shed": 0, "lat": []},
            )
            t["count"] += 1
            if not ok:
                t["errors"] += 1
            if status == 429:
                t["shed"] += 1
            # Shed requests answer in microseconds; folding them into the
            # tenant's latency would make a heavily-shed aggressor look
            # FAST.  Percentiles are over answered-with-data ops only.
            if status != 429:
                t["lat"].append(lat)
    ops_out: dict[str, dict] = {}
    for name, c in sorted(by_class.items()):
        lat = sorted(c["lat"])
        svc = sorted(c["svc"])
        ops_out[name] = {
            "count": c["count"],
            "errors": c["errors"],
            "errorRatio": c["errors"] / c["count"] if c["count"] else 0.0,
            "p50Ms": _ms(_percentile(lat, 0.50)),
            "p99Ms": _ms(_percentile(lat, 0.99)),
            "p999Ms": _ms(_percentile(lat, 0.999)),
            "serviceP50Ms": _ms(_percentile(svc, 0.50)),
            "serviceP99Ms": _ms(_percentile(svc, 0.99)),
        }
    tenants_out: dict[str, dict] = {}
    for name, t in sorted(by_tenant.items()):
        lat = sorted(t["lat"])
        tenants_out[name] = {
            "count": t["count"],
            "errors": t["errors"],
            "shed": t["shed"],
            "shedRatio": t["shed"] / t["count"] if t["count"] else 0.0,
            "p50Ms": _ms(_percentile(lat, 0.50)),
            "p99Ms": _ms(_percentile(lat, 0.99)),
        }
    total_ops = sum(c["count"] for c in ops_out.values())
    verdicts: dict[str, dict] = {}
    server_classes = (server_slo or {}).get("classes", {})
    for name, cls in server_classes.items():
        if cls.get("objective") is None:
            continue
        verdicts[name] = {
            "pass": bool(cls.get("ok")),
            "alerts": cls.get("alerts", {}),
            "latencyOk": cls.get("latencyOk"),
            "serverP99Ms": (cls.get("latency") or {}).get("p99Ms"),
        }
    overall = all(v["pass"] for v in verdicts.values()) if verdicts else None
    return {
        "schema": SCHEMA,
        "config": config,
        "stages": stages,
        "sequenceFingerprint": sequence_fingerprint,
        "wallSeconds": wall_seconds,
        "totalOps": total_ops,
        "throughputOpsPerSec": total_ops / wall_seconds if wall_seconds else 0.0,
        "clientErrors": client_errors,
        "ops": ops_out,
        "serverSLO": server_slo,
        "liveSLOServedDuringRun": live_slo_ok,
        "sloMetricsPresent": slo_metrics_present,
        # flight-recorder view after the run: incident bundles captured
        # by burning alerts / 504 spikes during the fault stages
        "incidents": (incidents or {}).get("incidents", []),
        # coordinator event journal after the run: the resize stage's
        # timeline (resize-start .. epoch-flip .. resize-commit) rides
        # here so SLO_r*.json is self-contained evidence of an online
        # membership change under load
        "events": (events or {}).get("events", []),
        # end-of-run residency + HBM-budget snapshots (docs/residency.md):
        # with an `oversubscribed` stage in the plan, the report carries
        # the device hit/miss and prefetch useful/issued rates the
        # working-set manager sustained under eviction pressure
        "residency": residency,
        # end-of-run semantic-cache snapshot (docs/caching.md); with a
        # repeat-heavy stage in the plan, the per-stage entries carry
        # the hit/invalidation deltas observed while it ran
        "rescache": rescache,
        # end-of-run flight-planner snapshot (docs/serving.md "Flight
        # planning"); with a shared-subtree stage in the plan, the
        # per-stage entries carry the cseHits/reorders deltas observed
        # while it ran
        "planner": planner,
        # end-of-run device cost ledger (docs/observability.md): per-site
        # compile/launch/transfer accounting plus per-principal rows —
        # tenant-labeled stages (StageSpec.tenant) land here under their
        # (tenant, index, opClass) principals; per-stage entries carry
        # the compile/launch/transfer deltas observed while each ran
        "devcosts": devcosts,
        # client-side per-tenant view of multi-tenant stages
        # (StageSpec.tenants): shed counts ride separately and are kept
        # OUT of the latency percentiles, so the aggressor's 429s don't
        # masquerade as fast service
        "opsByTenant": tenants_out,
        # end-of-run QoS governor snapshot (docs/robustness.md "Governed
        # admission"): per-tenant stage/debt/shed counters plus the
        # pressure-ladder transition journal observed during the run
        "qos": qos,
        # end-of-run metrics-history plane (docs/observability.md
        # "Metrics history & trend incidents"): sampler/tier state,
        # trend-detector baselines, and the run's `trend` incidents;
        # per-stage entries carry windowed series stats (mean/max/last
        # over exactly the samples recorded while each stage ran)
        "history": history,
        "verdicts": verdicts,
        "pass": overall,
    }


def _ms(v: float | None) -> float | None:
    return v * 1e3 if v is not None else None


def validate_report(report: dict) -> None:
    """Raise ValueError when the report breaks the schema contract."""
    if not isinstance(report, dict):
        raise ValueError("report must be a dict")
    if report.get("schema") != SCHEMA:
        raise ValueError(f"bad schema tag: {report.get('schema')!r}")
    for key in (
        "config", "stages", "sequenceFingerprint", "wallSeconds",
        "totalOps", "ops", "serverSLO", "verdicts",
        "liveSLOServedDuringRun", "sloMetricsPresent",
    ):
        if key not in report:
            raise ValueError(f"report missing key: {key}")
    if not isinstance(report["ops"], dict) or not report["ops"]:
        raise ValueError("report.ops must be a non-empty dict")
    for name, c in report["ops"].items():
        for key in ("count", "errors", "p50Ms", "p99Ms", "p999Ms"):
            if key not in c:
                raise ValueError(f"ops[{name!r}] missing {key}")
    slo = report["serverSLO"]
    if not isinstance(slo, dict) or "classes" not in slo:
        raise ValueError("serverSLO must carry a classes map")
    for name, v in report["verdicts"].items():
        if "pass" not in v:
            raise ValueError(f"verdicts[{name!r}] missing pass")


def next_report_path(directory: str = ".") -> str:
    """Next free SLO_rNN.json in ``directory`` (numbering mirrors the
    BENCH_r*.json convention)."""
    n = 1
    for entry in os.listdir(directory):
        if entry.startswith("SLO_r") and entry.endswith(".json"):
            digits = entry[len("SLO_r"):-len(".json")]
            if digits.isdigit():
                n = max(n, int(digits) + 1)
    return os.path.join(directory, f"SLO_r{n:02d}.json")
