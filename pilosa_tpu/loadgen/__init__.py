"""Seeded, deterministic open-loop load generation (the SLO plane's
workload half — see pilosa_tpu/obs/slo.py for the measurement half and
tools/loadharness.py for the CLI)."""

from pilosa_tpu.loadgen.harness import (
    LoadHarness,
    StageSpec,
    prepare_schema,
    preload,
    run_harness,
)
from pilosa_tpu.loadgen.report import (
    SCHEMA,
    build_report,
    next_report_path,
    validate_report,
)
from pilosa_tpu.loadgen.workload import (
    DEFAULT_MIX,
    OP_CLASS,
    Op,
    WorkloadConfig,
    WorkloadGenerator,
    Zipf,
    fingerprint,
)

__all__ = [
    "DEFAULT_MIX",
    "LoadHarness",
    "OP_CLASS",
    "Op",
    "SCHEMA",
    "StageSpec",
    "WorkloadConfig",
    "WorkloadGenerator",
    "Zipf",
    "build_report",
    "fingerprint",
    "next_report_path",
    "prepare_schema",
    "preload",
    "run_harness",
    "validate_report",
]
