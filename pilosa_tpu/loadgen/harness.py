"""Open-loop load harness: drive a deterministic workload through the
real HTTP path of an ``InProcessCluster`` and measure it against the
server's SLO plane.

Open-loop means arrivals are SCHEDULED, not request-response paced: op
``k`` of a stage targeting ``rate`` ops/s is due at ``t0 + k/rate``
regardless of how the previous op fared, and its latency is measured
from its *scheduled* time — the standard defense against coordinated
omission (a slow server can't slow the clock that judges it).  Workers
pull due ops from a bounded queue; when every worker is wedged the
dispatcher blocks on the queue and the lost schedule time is charged to
the ops' latencies, not silently dropped.

Stages ramp concurrency/rate and can override the op mix — the default
stage plan in tools/loadharness.py includes a time-quantum-heavy stage
(streaming timestamped SetBit with concurrent time-Range queries) and a
full-mix ramp.  Faults (testing/faults.py) can be injected for
error-budget exercises.
"""

from __future__ import annotations

import http.client
import json
import logging
import math
import queue
import threading
import time
import urllib.parse

from pilosa_tpu.loadgen import report as report_mod
from pilosa_tpu.loadgen.workload import (
    WorkloadConfig,
    WorkloadGenerator,
    fingerprint,
    schema_ops,
)

logger = logging.getLogger(__name__)

_HTTP_TIMEOUT = 30.0


class StageSpec:
    """One load stage: ``rate`` ops/s for ``duration`` seconds across
    ``workers`` concurrent connections, drawing kinds from ``mix``
    (None = the workload config's mix).

    ``device_budget`` (bytes) caps the process-wide HBM budget for the
    stage's duration and restores the previous cap after — the
    oversubscription knob: a stage whose working set exceeds the cap
    runs under live eviction pressure, and its report entry carries the
    residency hit/miss/prefetch rates observed while it ran
    (docs/residency.md).

    ``repeat_pool`` (template count) switches the stage's reads to the
    repeat-heavy generator (``WorkloadGenerator.sequence_repeat``):
    reads recur zipfian over that many fixed query templates while
    writes keep randomizing — the dashboard-refresh shape that
    exercises the semantic result cache, whose per-stage hit/
    invalidation deltas land in the report entry (docs/caching.md).

    ``shared_pool`` (template count) switches the stage's reads to the
    shared-subtree flight generator (``WorkloadGenerator.
    sequence_shared``): each read is one multi-call query whose calls
    embed a common canonical subtree, the shape the flight planner's
    cross-query CSE exists for — the stage's report entry carries the
    planner's per-stage cseHits/reorders deltas (docs/serving.md
    "Flight planning").

    ``tenant`` stamps every request of the stage with an
    ``X-Pilosa-Tenant`` header, so the stage's device work lands under
    that principal in the device cost ledger (docs/observability.md);
    the per-stage ``devcosts`` delta and the report's top-level
    ``devcosts`` block show the attribution.

    ``tenants`` (``{name: share}``) splits ONE stage's offered load
    across several tenants by weighted interleave — the QoS overload
    shape (docs/robustness.md "Governed admission"): a victim at share
    1 and an aggressor at share 10 ride the same open-loop schedule,
    and the report's per-tenant breakdown shows who got shed/degraded
    and who kept their latency.  Mutually exclusive with ``tenant``."""

    def __init__(
        self,
        name: str,
        duration: float,
        rate: float,
        workers: int,
        mix: dict[str, float] | None = None,
        device_budget: int | None = None,
        repeat_pool: int | None = None,
        tenant: str | None = None,
        shared_pool: int | None = None,
        tenants: dict[str, float] | None = None,
    ):
        self.name = name
        self.duration = float(duration)
        self.rate = float(rate)
        self.workers = int(workers)
        self.mix = mix
        self.device_budget = (
            int(device_budget) if device_budget is not None else None
        )
        self.repeat_pool = int(repeat_pool) if repeat_pool else None
        self.tenant = str(tenant) if tenant else None
        self.shared_pool = int(shared_pool) if shared_pool else None
        self.tenants = (
            {str(t): float(s) for t, s in tenants.items()} if tenants else None
        )
        if self.tenant and self.tenants:
            raise ValueError("tenant and tenants are mutually exclusive")

    @property
    def op_count(self) -> int:
        return max(1, int(math.ceil(self.duration * self.rate)))

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "duration": self.duration,
            "rate": self.rate,
            "workers": self.workers,
            "mix": self.mix,
            "deviceBudget": self.device_budget,
            "repeatPool": self.repeat_pool,
            "tenant": self.tenant,
            "sharedPool": self.shared_pool,
            "tenants": self.tenants,
        }


def prepare_schema(cluster, config: WorkloadConfig) -> None:
    """Create the workload's indexes/fields through the API (idempotent
    across harness reruns on one cluster)."""
    from pilosa_tpu.server.api import ConflictError

    for kind, name, options in schema_ops(config):
        try:
            if kind == "index":
                cluster.create_index(name, options)
            else:
                index, _, field = name.partition("/")
                cluster.create_field(index, field, options)
        except ConflictError:
            pass


def preload(cluster, config: WorkloadConfig, bits: int = 4096) -> None:
    """Deterministic seed data so reads have something to find: zipfian
    (row, col) pairs into the segmentation field, plus int values into
    the BSI field so range_bsi predicates select non-empty rows from the
    first request (not only after set_val writes accumulate)."""
    import numpy as np

    from pilosa_tpu.loadgen.workload import (
        BSI_FIELD,
        BSI_VAL_MAX,
        BSI_VAL_MIN,
        Zipf,
    )

    rng = np.random.default_rng(config.seed ^ 0x5EED)
    rz = Zipf(config.n_rows, config.zipf_theta)
    cz = Zipf(config.n_cols, config.zipf_theta)
    pairs = [(rz.sample(rng), cz.sample(rng)) for _ in range(bits)]
    cluster.import_bits(config.index, "seg", pairs)
    vcols = sorted({cz.sample(rng) for _ in range(bits // 2)})
    vvals = [
        int(v)
        for v in rng.integers(BSI_VAL_MIN, BSI_VAL_MAX, size=len(vcols))
    ]
    cluster.import_values(config.index, BSI_FIELD, vcols, vvals)


class _WorkerResult:
    __slots__ = ("records", "client_errors")

    def __init__(self):
        # (op_class, latency_s, service_s, ok, status, tenant)
        self.records: list[tuple[str, float, float, bool, int, str | None]] = []
        self.client_errors = 0


def _tenant_schedule(tenants: dict[str, float], n: int) -> list[str]:
    """Deterministic weighted interleave of ``n`` slots across tenants.

    Credit-based (smooth weighted round-robin): every slot each tenant
    earns its share, the richest tenant is picked and pays the total.
    A {victim: 1, aggressor: 10} split therefore ISSUES interleaved —
    the victim's requests are spread through the aggressor's flood, not
    batched before/after it, so the governor sees concurrent pressure."""
    names = sorted(tenants)
    total = sum(tenants[t] for t in names) or 1.0
    credit = dict.fromkeys(names, 0.0)
    out: list[str] = []
    for _ in range(n):
        for t in names:
            credit[t] += tenants[t]
        pick = max(names, key=lambda t: (credit[t], t))
        credit[pick] -= total
        out.append(pick)
    return out


def _worker(
    base: str,
    q: "queue.Queue",
    out: _WorkerResult,
    stop: threading.Event,
    tenant: str | None = None,
) -> None:
    netloc = urllib.parse.urlsplit(base).netloc
    conn = http.client.HTTPConnection(netloc, timeout=_HTTP_TIMEOUT)
    headers = {"Content-Type": ""}
    try:
        while not stop.is_set():
            item = q.get()
            if item is None:
                return
            op, sched, op_tenant = item
            eff_tenant = op_tenant or tenant
            now = time.monotonic()
            if sched > now:
                time.sleep(sched - now)
            t_start = time.monotonic()
            status = 0
            try:
                headers["Content-Type"] = op.ctype
                if eff_tenant:
                    headers["X-Pilosa-Tenant"] = eff_tenant
                else:
                    headers.pop("X-Pilosa-Tenant", None)
                conn.request(
                    op.method,
                    op.path,
                    body=op.body,
                    headers=headers,
                )
                resp = conn.getresponse()
                resp.read()
                status = resp.status
            except (OSError, http.client.HTTPException):
                # connection-level failure: count it, reconnect, move on
                out.client_errors += 1
                conn.close()
                conn = http.client.HTTPConnection(netloc, timeout=_HTTP_TIMEOUT)
            done = time.monotonic()
            ok = 200 <= status < 400
            out.records.append(
                (op.op_class, done - sched, done - t_start, ok, status,
                 eff_tenant)
            )
    finally:
        conn.close()


def _fetch_json(base: str, path: str) -> dict | None:
    netloc = urllib.parse.urlsplit(base).netloc
    conn = http.client.HTTPConnection(netloc, timeout=_HTTP_TIMEOUT)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read()
        if resp.status != 200:
            return None
        return json.loads(body)
    except (OSError, ValueError, http.client.HTTPException):
        return None
    finally:
        conn.close()


def _residency_counters(base: str) -> dict | None:
    """Monotonic residency counters from /debug/vars, flattened for
    delta arithmetic (None when the node predates the residency plane)."""
    dbg = _fetch_json(base, "/debug/vars")
    if not dbg or "residency" not in dbg:
        return None
    res = dbg.get("residency") or {}
    dev = dbg.get("device") or {}
    return {
        "deviceHits": res.get("deviceHits", 0),
        "deviceMisses": res.get("deviceMisses", 0),
        "prefetchIssued": res.get("prefetchIssued", 0),
        "prefetchUseful": res.get("prefetchUseful", 0),
        "evictions": dev.get("evictions", 0),
    }


def _residency_delta(
    before: dict | None, after: dict | None
) -> dict | None:
    if before is None or after is None:
        return None
    delta = {k: after[k] - before[k] for k in before}
    lookups = delta["deviceHits"] + delta["deviceMisses"]
    delta["hitRate"] = (
        delta["deviceHits"] / lookups if lookups else None
    )
    issued = delta["prefetchIssued"]
    delta["prefetchUsefulFrac"] = (
        delta["prefetchUseful"] / issued if issued else None
    )
    return delta


def _rescache_counters(base: str) -> dict | None:
    """Monotonic semantic-cache counters from /debug/vars, for per-stage
    delta arithmetic (None when the node predates the cache plane)."""
    dbg = _fetch_json(base, "/debug/vars")
    if not dbg or "rescache" not in dbg:
        return None
    rc = dbg.get("rescache") or {}
    batcher = dbg.get("batcher") or {}
    return {
        "hits": rc.get("hits", 0),
        "misses": rc.get("misses", 0),
        "invalidations": rc.get("invalidations", 0),
        "promotions": rc.get("promotions", 0),
        "maintainedHits": rc.get("maintainedHits", 0),
        "rescacheDemux": batcher.get("rescacheDemux", 0),
    }


def _rescache_delta(before: dict | None, after: dict | None) -> dict | None:
    if before is None or after is None:
        return None
    delta = {k: after[k] - before[k] for k in before}
    lookups = delta["hits"] + delta["misses"]
    delta["hitRate"] = delta["hits"] / lookups if lookups else None
    return delta


def _planner_counters(base: str) -> dict | None:
    """Monotonic flight-planner counters from /debug/vars, for per-stage
    delta arithmetic (None when the node predates the planner)."""
    dbg = _fetch_json(base, "/debug/vars")
    if not dbg or "planner" not in dbg:
        return None
    pl = dbg.get("planner") or {}
    return {
        "cseHits": pl.get("cseHits", 0),
        "cseShared": pl.get("cseShared", 0),
        "reorders": pl.get("reorders", 0),
        "laneOverrides": pl.get("laneOverrides", 0),
        "errors": pl.get("errors", 0),
    }


def _planner_delta(before: dict | None, after: dict | None) -> dict | None:
    if before is None or after is None:
        return None
    return {k: after[k] - before[k] for k in before}


def _devcost_counters(base: str) -> dict | None:
    """Monotonic device-cost-ledger totals from /debug/devcosts,
    flattened for per-stage delta arithmetic (None when the node
    predates the device cost ledger)."""
    dc = _fetch_json(base, "/debug/devcosts")
    if not dc or "totals" not in dc:
        return None
    tot = dc.get("totals") or {}
    return {
        "compiles": tot.get("compiles", 0),
        "compileMs": tot.get("compileMs", 0.0),
        "launches": tot.get("launches", 0),
        "deviceMs": tot.get("deviceMs", 0.0),
        "transferBytes": tot.get("h2dBytes", 0) + tot.get("d2hBytes", 0),
        "storms": len((dc.get("storm") or {}).get("recent", [])),
    }


def _devcost_delta(before: dict | None, after: dict | None) -> dict | None:
    if before is None or after is None:
        return None
    return {k: round(after[k] - before[k], 3) for k in before}


def _qos_counters(base: str) -> dict | None:
    """Monotonic per-tenant QoS governor counters from /debug/qos, for
    per-stage delta arithmetic (None when the node predates the
    governor or it is disabled)."""
    snap = _fetch_json(base, "/debug/qos")
    if not snap or not snap.get("enabled"):
        return None
    return {
        t: {
            "admitted": st.get("admitted", 0),
            "served": st.get("served", 0),
            "shed": st.get("shed", 0),
            "degraded": st.get("degraded", 0),
            "debtMs": st.get("debtMs", 0.0),
        }
        for t, st in (snap.get("tenants") or {}).items()
    }


def _qos_delta(before: dict | None, after: dict | None) -> dict | None:
    if before is None or after is None:
        return None
    out = {}
    for t, av in after.items():
        bv = before.get(t) or {}
        out[t] = {k: round(av[k] - bv.get(k, 0), 3) for k in av}
    return out


# per-stage history embedding: the headline series whose windowed stats
# land in each stage's report entry (full point lists stay on the node)
_HISTORY_STAGE_SERIES = (
    "slo.*.p99_ms,slo.*.rps,slo.*.availability,batcher.depth,"
    "dev.device_ms_ps"
)


def _history_cursor(base: str) -> int | None:
    """The metrics-history base-seq cursor NOW (None when the node
    predates /debug/history or runs with the plane disabled)."""
    snap = _fetch_json(base, "/debug/history?limit=0")
    if not snap or "nextSeq" not in snap:
        return None
    return snap["nextSeq"]


def _history_stage_delta(base: str, since: int | None) -> dict | None:
    """Summary stats (mean/max/last) over the series samples the
    history plane recorded DURING one stage — the ?since= cursor makes
    the window exactly the stage's own samples, and the gap-honest
    ``truncated`` flag rides along so a stage that outran the base
    ring says so instead of silently shrinking."""
    if since is None:
        return None
    snap = _fetch_json(
        base,
        f"/debug/history?since={int(since)}"
        f"&series={urllib.parse.quote(_HISTORY_STAGE_SERIES, safe='')}",
    )
    if not snap:
        return None
    out = {
        "samples": snap.get("returned", 0),
        "truncated": bool(snap.get("truncated")),
        "series": {},
    }
    for name, pts in (snap.get("series") or {}).items():
        vals = [v for _, v in pts if v is not None]
        if not vals:
            continue
        out["series"][name] = {
            "mean": round(sum(vals) / len(vals), 4),
            "max": round(max(vals), 4),
            "last": round(vals[-1], 4),
        }
    return out


def _fetch_text(base: str, path: str) -> str:
    netloc = urllib.parse.urlsplit(base).netloc
    conn = http.client.HTTPConnection(netloc, timeout=_HTTP_TIMEOUT)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.read().decode("utf-8", "replace")
    except (OSError, http.client.HTTPException):
        return ""
    finally:
        conn.close()


class LoadHarness:
    """Runs staged open-loop load against cluster node URIs and builds
    the SLO report dict (see loadgen/report.py for the schema)."""

    # A stage dips below this ok-ratio -> its availability verdict fails
    # (the resize stage's contract: no cluster-wide error window).
    AVAILABILITY_FLOOR = 0.99

    def __init__(
        self,
        uris: list[str],
        config: WorkloadConfig,
        stages: list[StageSpec],
        stage_hooks: dict | None = None,
        availability_floor: float | None = None,
    ):
        if not uris:
            raise ValueError("at least one node URI required")
        self.uris = list(uris)
        self.config = config
        self.stages = list(stages)
        # name -> zero-arg callable run CONCURRENTLY with that stage's
        # traffic (the resize stage's add/remove-node driver); the stage
        # doesn't end until the hook returns, and a hook exception lands
        # in the stage's report entry instead of killing the run.
        self.stage_hooks = dict(stage_hooks or {})
        self.availability_floor = (
            self.AVAILABILITY_FLOOR
            if availability_floor is None
            else float(availability_floor)
        )

    def generate(self) -> list[list]:
        """Pre-generate every stage's op sequence (the full request
        sequence is fixed before the first byte hits the wire); one
        generator stream spans the stages so the whole run replays from
        the seed."""
        gen = WorkloadGenerator(self.config)

        def _stage_ops(st: StageSpec) -> list:
            if st.shared_pool:
                return gen.sequence_shared(
                    st.op_count, st.mix, pool_size=st.shared_pool
                )
            if st.repeat_pool:
                return gen.sequence_repeat(
                    st.op_count, st.mix, pool_size=st.repeat_pool
                )
            return gen.sequence(st.op_count, st.mix)

        return [_stage_ops(st) for st in self.stages]

    def run(self) -> dict:
        per_stage_ops = self.generate()
        all_ops = [op for ops in per_stage_ops for op in ops]
        seq_fp = fingerprint(all_ops)
        live_snapshot = None
        results: list[_WorkerResult] = []
        stage_meta = []
        t_run0 = time.monotonic()
        for si, (stage, ops) in enumerate(zip(self.stages, per_stage_ops)):
            # Oversubscription knob: cap the process-wide HBM budget for
            # this stage only (the harness shares the servers' process —
            # InProcessCluster — so the budget singleton is reachable
            # directly), and restore the previous cap after the join so
            # later stages run at their configured residency.  set_cap
            # (not configure) so entries admitted by earlier stages stay
            # accounted and the shrink evicts the live working set.
            res_before = _residency_counters(self.uris[0])
            rc_before = _rescache_counters(self.uris[0])
            pl_before = _planner_counters(self.uris[0])
            dc_before = _devcost_counters(self.uris[0])
            qo_before = _qos_counters(self.uris[0])
            hi_before = _history_cursor(self.uris[0])
            prev_cap: tuple | None = None
            if stage.device_budget is not None:
                from pilosa_tpu.core import membudget

                prev_cap = (membudget.default_budget().cap,)
                # after the counter snapshot: the shrink's trim evictions
                # belong to this stage's delta
                membudget.set_cap(stage.device_budget)
            stop = threading.Event()
            q: "queue.Queue" = queue.Queue(maxsize=max(64, stage.workers * 8))
            outs = [_WorkerResult() for _ in range(stage.workers)]
            threads = [
                threading.Thread(
                    target=_worker,
                    args=(
                        self.uris[w % len(self.uris)], q, outs[w], stop,
                        stage.tenant,
                    ),
                    name=f"loadgen-{stage.name}-{w}",
                    daemon=True,
                )
                for w in range(stage.workers)
            ]
            for t in threads:
                t.start()
            hook_thread = None
            hook_errors: list[str] = []
            hook = self.stage_hooks.get(stage.name)
            if hook is not None:
                def _run_hook(fn=hook, errs=hook_errors):
                    try:
                        fn()
                    except Exception as e:  # graftlint: disable=exception-hygiene -- surfaced in the stage's report entry; the load run must finish either way
                        logger.exception("stage hook failed")
                        errs.append(f"{type(e).__name__}: {e}")

                hook_thread = threading.Thread(
                    target=_run_hook, name=f"loadgen-hook-{stage.name}",
                    daemon=True,
                )
                hook_thread.start()
            tenant_seq = (
                _tenant_schedule(stage.tenants, len(ops))
                if stage.tenants
                else None
            )
            t0 = time.monotonic()
            interval = 1.0 / stage.rate if stage.rate > 0 else 0.0
            for k, op in enumerate(ops):
                q.put((
                    op,
                    t0 + k * interval,
                    tenant_seq[k] if tenant_seq else None,
                ))
            for _ in threads:
                q.put(None)
            # mid-run liveness probe: /debug/slo must serve DURING load
            if si == 0:
                live_snapshot = _fetch_json(self.uris[0], "/debug/slo")
            for t in threads:
                t.join()
            if hook_thread is not None:
                hook_thread.join()
            stop.set()
            if prev_cap is not None:
                from pilosa_tpu.core import membudget

                membudget.set_cap(prev_cap[0])
            results.extend(outs)
            # Per-stage availability verdict: the share of this stage's
            # ops answered 2xx/3xx.  The resize stage's acceptance rides
            # on this — membership changes must not open an error window.
            ok_ops = sum(
                1 for o in outs for r in o.records if r[3]
            )
            stage_client_errors = sum(o.client_errors for o in outs)
            availability = ok_ops / len(ops) if ops else 1.0
            stage_meta.append(
                {
                    **stage.to_dict(),
                    "ops": len(ops),
                    "okOps": ok_ops,
                    "clientErrors": stage_client_errors,
                    "availability": availability,
                    "availabilityOk": availability >= self.availability_floor,
                    "hookRan": hook is not None,
                    "hookError": hook_errors[0] if hook_errors else None,
                    "residency": _residency_delta(
                        res_before, _residency_counters(self.uris[0])
                    ),
                    "rescache": _rescache_delta(
                        rc_before, _rescache_counters(self.uris[0])
                    ),
                    "planner": _planner_delta(
                        pl_before, _planner_counters(self.uris[0])
                    ),
                    "devcosts": _devcost_delta(
                        dc_before, _devcost_counters(self.uris[0])
                    ),
                    "qos": _qos_delta(
                        qo_before, _qos_counters(self.uris[0])
                    ),
                    "history": _history_stage_delta(
                        self.uris[0], hi_before
                    ),
                }
            )
        wall = time.monotonic() - t_run0
        records = [r for out in results for r in out.records]
        client_errors = sum(out.client_errors for out in results)
        server_slo = _fetch_json(self.uris[0], "/debug/slo")
        metrics_text = _fetch_text(self.uris[0], "/metrics")
        incidents = _fetch_json(self.uris[0], "/debug/incidents")
        events = _fetch_json(self.uris[0], "/debug/events")
        final_vars = _fetch_json(self.uris[0], "/debug/vars")
        residency = None
        if final_vars and "residency" in final_vars:
            residency = {
                "residency": final_vars.get("residency"),
                "device": final_vars.get("device"),
            }
        rescache = None
        if final_vars and "rescache" in final_vars:
            rescache = final_vars.get("rescache")
        planner = None
        if final_vars and "planner" in final_vars:
            planner = final_vars.get("planner")
        # end-of-run ledger state: per-site and per-principal accounting
        # (the tenant-labeled stages show up as principals here)
        devcosts = _fetch_json(self.uris[0], "/debug/devcosts")
        # end-of-run governor state: per-tenant stages, debt, transitions
        qos = _fetch_json(self.uris[0], "/debug/qos")
        # end-of-run history plane: sampler/tier state, detector
        # baselines, and the run's trend incidents (each bundle carries
        # its own pre-incident series windows at /debug/incidents?id=)
        history = None
        hist_snap = _fetch_json(self.uris[0], "/debug/history?limit=0")
        if hist_snap and "nextSeq" in hist_snap:
            trend = []
            for inc in (incidents or {}).get("incidents", []):
                if (inc.get("trigger") or {}).get("type") != "trend":
                    continue
                # the bundle detail carries the attached series windows;
                # embed their span (not the points — the full evidence
                # stays at /debug/incidents?id=)
                entry = dict(inc)
                detail = _fetch_json(
                    self.uris[0], f"/debug/incidents?id={inc['id']}"
                )
                series = (detail or {}).get("series") or {}
                entry["preSeconds"] = series.get("preSeconds")
                entry["seriesCount"] = len(series.get("series") or {})
                trend.append(entry)
            history = {
                "samples": hist_snap.get("seq"),
                "cadence": hist_snap.get("cadence"),
                "tiers": hist_snap.get("tiers"),
                "detectors": hist_snap.get("detectors"),
                "trendIncidents": trend,
            }
        return report_mod.build_report(
            config=self.config.to_dict(),
            stages=stage_meta,
            records=records,
            client_errors=client_errors,
            wall_seconds=wall,
            sequence_fingerprint=seq_fp,
            server_slo=server_slo,
            live_slo_ok=bool(live_snapshot and live_snapshot.get("classes") is not None),
            slo_metrics_present="pilosa_slo_requests_total" in metrics_text,
            incidents=incidents,
            events=events,
            residency=residency,
            rescache=rescache,
            planner=planner,
            devcosts=devcosts,
            qos=qos,
            history=history,
        )


def run_harness(
    config: WorkloadConfig,
    stages: list[StageSpec],
    nodes: int = 1,
    cluster_kwargs: dict | None = None,
    faults: list[dict] | None = None,
    preload_bits: int = 4096,
    stage_hooks: dict | None = None,
) -> dict:
    """Boot an InProcessCluster, prepare schema + seed data, drive the
    staged workload, and return the report dict.  ``cluster_kwargs``
    passes through to InProcessCluster (SLO window knobs etc.);
    ``faults`` is a list of ``inject_fault`` kwargs dicts.
    ``stage_hooks`` maps stage name -> callable(cluster) run concurrently
    with that stage's traffic (e.g. add/remove a node mid-zipfian)."""
    from pilosa_tpu.testing.cluster import InProcessCluster

    kwargs = dict(cluster_kwargs or {})
    with InProcessCluster(nodes, **kwargs) as cluster:
        prepare_schema(cluster, config)
        if preload_bits:
            preload(cluster, config, preload_bits)
        for f in faults or []:
            cluster.inject_fault(**f)
        bound_hooks = {
            name: (lambda fn=fn: fn(cluster))
            for name, fn in (stage_hooks or {}).items()
        }
        harness = LoadHarness(
            [n.uri for n in cluster.nodes], config, stages,
            stage_hooks=bound_hooks,
        )
        return harness.run()
