"""Staged ingest pipeline: decode -> coalesced apply -> H2D upload.

The lock-step import path serialized everything: decode a batch, merge
it into the fragment's host mirror, (eventually) re-upload the fragment
to HBM, repeat.  The pipeline runs the three stages concurrently over a
stream of per-shard segments, tf.data-style (overlap the transfer with
the compute):

* **decode** — Roaring blob -> positions, natively and zero-copy into a
  pinned staging buffer (staging.py).  Runs on the submitting handler
  thread; bounded by the staging pool.
* **apply** — the fragment merge, on the bounded ImportPool.  Every
  segment is submitted before any is awaited, so distinct fragments
  drain on different workers, and same-fragment segments group-commit
  into one merged apply (importpool.submit_merged).
* **upload** — the host->device sync of an applied fragment, on a
  dedicated double-buffered uploader thread: while batch N+1 is being
  merged on a worker, batch N's HBM upload is in flight here.  Two
  slots (classic double buffering) bound the device-sync backlog; a
  full slot queue blocks the apply stage, which blocks the pool queue,
  which blocks the HTTP client — backpressure end to end.

``overlap_frac`` reports the fraction of uploaded bytes whose transfer
ran while an apply was in flight — the overlap the pipeline exists to
create (kernels.py's h2d/d2h telemetry showed the lock-step path
spending that time stalled).
"""

from __future__ import annotations

import queue
import threading
import time

from pilosa_tpu.ingest.staging import DEFAULT_CAPACITY, StagingPool
from pilosa_tpu.obs import devledger

# Device cost ledger sites: upload windows adopt the fragment sync's
# compiles and H2D bytes (kernels.note_transfer books to the active
# window's site), splitting ingest uploads from predictive prefetches.
_DL_UPLOAD = devledger.site("ingest.upload")
_DL_PREFETCH = devledger.site("server.prefetch")

_STOP = object()


class DeviceUploader:
    """Double-buffered background host->device sync stage, shared
    between ingest and the residency prefetcher.

    ``submit(frag)`` enqueues a fragment whose mirror was just mutated;
    the uploader thread calls ``frag.device_bits()`` (the incremental
    word/row-scatter sync) off the apply path.  The slot queue is the
    double buffer: with the default two slots, one upload can be in
    flight while one more is staged, and a third submission blocks its
    apply worker (bounded backlog, propagated backpressure).

    ``submit_prefetch(frag)`` rides the same thread on a SECOND,
    lower-priority queue: the run loop only takes a prefetch item when
    the ingest queue is empty, so predictive uploads for the next query
    flight (server/batcher.py) can never delay an apply worker's sync.
    Prefetch submission never blocks — a full prefetch queue drops the
    item (the query path just pays its own upload, as before)."""

    def __init__(self, slots: int = 2, stats=None, applies_active=None):
        self.stats = stats
        self._applies_active = applies_active or (lambda: 0)
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, slots))
        self.slots = max(1, slots)
        # prefetch backlog is wider than the ingest double buffer (a
        # flight can stage many fragments at once) but still bounded:
        # drop-on-full, never block
        self._prefetch_q: "queue.Queue" = queue.Queue(
            maxsize=max(8, slots * 8)
        )
        self.uploads = 0
        self.uploads_coalesced = 0
        self.upload_errors = 0
        self.h2d_bytes = 0
        self.h2d_bytes_overlapped = 0
        self.blocked_submits = 0
        self.blocked_seconds = 0.0
        self.upload_seconds = 0.0
        self.prefetch_uploads = 0
        self.prefetch_dropped = 0
        self.prefetch_seconds = 0.0
        self._pending = 0
        self._queued: set[int] = set()  # id(frag) staged, not yet syncing
        self._prefetch_queued: set[int] = set()
        self._pending_lock = threading.Lock()
        self._idle = threading.Condition(self._pending_lock)
        self._wake = threading.Condition(self._pending_lock)
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="ingest-upload", daemon=True
        )
        self._thread.start()

    def submit(self, frag) -> None:
        """Queue a fragment for device sync; blocks while both slots are
        busy.  No-op after close (host mirror stays source of truth —
        the next query's device_bits() syncs lazily).

        Pending syncs coalesce: a fragment already staged (queued, sync
        not yet started) absorbs this submission — device_bits() reads
        the latest host state when it runs, so one sync covers every
        apply that landed before it started.  Back-to-back merges into
        one fragment cost ONE upload, not one per batch."""
        if self._closed:
            return
        with self._pending_lock:
            if id(frag) in self._queued:
                self.uploads_coalesced += 1
                if self.stats is not None:
                    self.stats.count("ingest_uploads_coalesced", 1)
                return
            self._queued.add(id(frag))
            self._pending += 1
            self._wake.notify()
        try:
            self._q.put_nowait(frag)
            return
        except queue.Full:
            pass
        self.blocked_submits += 1
        t0 = time.perf_counter()
        self._q.put(frag)
        self.blocked_seconds += time.perf_counter() - t0

    def submit_prefetch(self, frag, done=None) -> bool:
        """Stage a predictive upload on the low-priority queue; returns
        True when actually queued.  Never blocks: a full queue or an
        uploader busy with the same fragment's ingest sync drops the
        request (False), and the query path pays its own upload exactly
        as it would have without prefetch.  ``done(frag, err)`` runs on
        the uploader thread after the sync attempt."""
        if self._closed:
            return False
        # stack targets carry a stable identity across flights; raw
        # fragments dedup on object id exactly like the ingest queue
        key = getattr(frag, "prefetch_key", None)
        if key is None:
            key = id(frag)
        with self._pending_lock:
            if id(frag) in self._queued or key in self._prefetch_queued:
                # already riding an ingest sync / earlier prefetch: that
                # upload covers this request (device_bits reads latest)
                return False
            self._prefetch_queued.add(key)
            self._pending += 1
            self._wake.notify()
        try:
            self._prefetch_q.put_nowait((frag, key, done))
            return True
        except queue.Full:
            self.prefetch_dropped += 1
            with self._pending_lock:
                self._prefetch_queued.discard(key)
                self._pending -= 1
                if self._pending == 0:
                    self._idle.notify_all()
            return False

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until every submitted upload has completed."""
        deadline = time.monotonic() + timeout
        with self._idle:
            while self._pending > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(remaining)
        return True

    def _drain_prefetch(self) -> None:
        """Discard staged prefetches at shutdown (predictive uploads are
        advisory; flush() was the owner's chance to wait them out)."""
        while True:
            try:
                self._prefetch_q.get_nowait()
            except queue.Empty:
                break
            with self._idle:
                self._pending -= 1
                if self._pending == 0:
                    self._idle.notify_all()
        with self._pending_lock:
            self._prefetch_queued.clear()

    def _run_prefetch(self, frag, done) -> None:
        """One predictive upload: marked as prefetch traffic so the
        residency tracker books it apart from query hits/misses."""
        from pilosa_tpu.core import residency

        t0 = time.perf_counter()
        err = None
        tracker = residency.default_tracker()
        tracker.enter_prefetch()
        try:
            with _DL_PREFETCH.launch(sig="prefetch_sync"):
                frag.device_bits()
        except Exception as e:  # advisory: the query path syncs lazily
            err = e
        finally:
            tracker.exit_prefetch()
        self.prefetch_uploads += 1
        self.prefetch_seconds += time.perf_counter() - t0
        if self.stats is not None:
            self.stats.count("residency_prefetch_uploads", 1)
        if done is not None:
            try:
                done(frag, err)
            except Exception:
                # the done callback is the prefetcher's own accounting
                # hook; a bug there must not kill the uploader thread
                tracker.note_prefetch_error()
        with self._idle:
            self._pending -= 1
            if self._pending == 0:
                self._idle.notify_all()

    def _run(self) -> None:
        while True:
            done = None
            pkey = None
            is_prefetch = False
            try:
                frag = self._q.get_nowait()
            except queue.Empty:
                # ingest queue empty: a prefetch may ride the idle slot
                # (strict priority — ingest is always drained first)
                try:
                    frag, pkey, done = self._prefetch_q.get_nowait()
                    is_prefetch = True
                except queue.Empty:
                    with self._wake:
                        if self._q.empty() and self._prefetch_q.empty():
                            self._wake.wait(0.05)
                    continue
            if frag is None:
                self._drain_prefetch()
                return
            # un-stage BEFORE syncing: an apply landing mid-sync must
            # queue a fresh sync (device_bits only covers state that
            # existed when it took the fragment lock)
            with self._pending_lock:
                if is_prefetch:
                    self._prefetch_queued.discard(pkey)
                else:
                    self._queued.discard(id(frag))
            if is_prefetch:
                self._run_prefetch(frag, done)
                continue
            overlapped = self._applies_active() > 0
            t0 = time.perf_counter()
            nbytes = 0
            try:
                with _DL_UPLOAD.launch(sig="ingest_sync"):
                    frag.device_bits()
                nbytes = int(getattr(frag, "last_sync_h2d_bytes", 0))
            except Exception:
                # Upload is an accelerator warm-path optimization; the
                # host mirror stays authoritative and the next query
                # syncs lazily, so a failed upload must not fail ingest.
                self.upload_errors += 1
                if self.stats is not None:
                    self.stats.count("ingest_upload_errors", 1)
            dt = time.perf_counter() - t0
            # overlapped if an apply was running when the upload started
            # or by the time it finished (the stages genuinely shared
            # wall-clock either way)
            overlapped = overlapped or self._applies_active() > 0
            self.uploads += 1
            self.upload_seconds += dt
            self.h2d_bytes += nbytes
            if overlapped:
                self.h2d_bytes_overlapped += nbytes
            if self.stats is not None:
                self.stats.count("ingest_uploads", 1)
                self.stats.count("ingest_h2d_bytes", nbytes)
                if overlapped:
                    self.stats.count("ingest_h2d_bytes_overlapped", nbytes)
                self.stats.timing("ingest_upload", dt)
            with self._idle:
                self._pending -= 1
                if self._pending == 0:
                    self._idle.notify_all()

    @property
    def overlap_frac(self) -> float:
        return (
            self.h2d_bytes_overlapped / self.h2d_bytes if self.h2d_bytes else 0.0
        )

    def snapshot(self) -> dict:
        return {
            "slots": self.slots,
            "uploads": self.uploads,
            "uploadsCoalesced": self.uploads_coalesced,
            "uploadErrors": self.upload_errors,
            "h2dBytes": self.h2d_bytes,
            "h2dBytesOverlapped": self.h2d_bytes_overlapped,
            "overlapFrac": round(self.overlap_frac, 4),
            "blockedSubmits": self.blocked_submits,
            "blockedSeconds": round(self.blocked_seconds, 6),
            "uploadSeconds": round(self.upload_seconds, 6),
            "prefetchUploads": self.prefetch_uploads,
            "prefetchDropped": self.prefetch_dropped,
            "prefetchSeconds": round(self.prefetch_seconds, 6),
        }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        with self._pending_lock:
            self._wake.notify()
        self._thread.join(timeout=5)


class ChunkPrefetcher:
    """Double-buffered read-ahead for sequential chunked transfers —
    the DeviceUploader's bounded-slot pattern pointed the other way.

    Fragment migration (cluster/resize.py) pulls a snapshot in chunks
    over HTTP; fetching chunk N+1 while chunk N is being applied hides
    the network RTT behind the apply, exactly like the uploader hides
    H2D transfers behind merges.  A worker thread fetches sequential
    chunks into a slot-bounded queue; the consumer iterates
    ``(offset, blob)`` pairs.  A fetch error surfaces on the consumer
    at the failed chunk's position, with ``next_offset`` telling a
    retry where to resume — everything before it was already consumed.
    """

    def __init__(self, fetch, size: int, chunk_bytes: int, slots: int = 2,
                 start: int = 0):
        self._fetch = fetch  # fn(offset) -> bytes
        self.size = max(0, int(size))
        self.chunk_bytes = max(1, int(chunk_bytes))
        self.next_offset = max(0, int(start))  # first unconsumed offset
        self.chunks = 0
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, slots))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="migrate-prefetch", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        offset = self.next_offset
        try:
            while offset < self.size and not self._stop.is_set():
                blob = self._fetch(offset)
                if not blob:
                    raise IOError(f"empty chunk at offset {offset}")
                self._q.put((offset, blob))
                offset += len(blob)
            self._q.put(None)  # clean end of stream
        except Exception as e:  # delivered to the consumer, not lost
            self._q.put(e)

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            if isinstance(item, Exception):
                raise item
            offset, blob = item
            yield offset, blob
            self.next_offset = offset + len(blob)
            self.chunks += 1

    def close(self) -> None:
        self._stop.set()
        # unblock a producer waiting on a full slot queue
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)


class IngestPipeline:
    """Orchestrates the staged import over an ImportPool.

    The pipeline owns the staging pool (decode stage) and the device
    uploader (transfer stage); the apply stage rides the shared
    ImportPool.  API import paths feed it per-shard segments; each
    segment's ``apply`` callback returns ``(result, fragment)`` and the
    fragment (when not None) is handed to the uploader."""

    def __init__(
        self,
        pool,
        stats=None,
        staging_buffers: int = 4,
        staging_capacity: int = DEFAULT_CAPACITY,
        upload_slots: int = 2,
        upload: bool = True,
    ):
        self.pool = pool
        self.stats = stats
        self.staging = StagingPool(
            buffers=staging_buffers, capacity=staging_capacity, stats=stats
        )
        self._applies = 0
        self._applies_lock = threading.Lock()
        self.uploader = (
            DeviceUploader(
                slots=upload_slots, stats=stats,
                applies_active=self.applies_active,
            )
            if upload
            else None
        )
        self.decoded = 0
        self.decode_seconds = 0.0
        self.segments = 0
        # post-apply observer: called with the mutated fragment inside
        # the same group-commit, before the upload stage sees it.  The
        # API wires this to the semantic result cache so a write
        # invalidates (or delta-maintains) entries the moment the merge
        # lands, not when the next query's version probe notices.
        self.on_apply = None

    def applies_active(self) -> int:
        with self._applies_lock:
            return self._applies

    # -- stage 1: decode ------------------------------------------------------

    def decode_roaring(self, data: bytes):
        """Decode a Roaring blob into a staging buffer (zero-copy native
        path); returns the held StagingBuffer.  The apply stage must
        release it."""
        self.pool.note_phase("decode")
        buf = self.staging.acquire()
        t0 = time.perf_counter()
        try:
            buf.decode_grow(data)
        except BaseException:
            buf.release()
            raise
        self.decode_seconds += time.perf_counter() - t0
        self.decoded += 1
        self.pool.advance(decoded=1)
        return buf

    # -- stage 2+3: coalesced apply, then upload ------------------------------

    def submit_segment(self, key, payload, apply_group, release=None):
        """Queue one per-shard segment for a (possibly coalesced) merged
        apply.  ``apply_group(payloads)`` runs on a pool worker with the
        arrival-ordered payload list of its group and returns
        ``(result, fragment)``; the fragment is then submitted to the
        upload stage.  ``release(payload)`` runs after the apply (even
        on error) — staging buffers are returned here, so a failed drain
        can't strand them."""
        self.segments += 1

        def fn_many(payloads):
            self.pool.note_phase("apply")
            with self._applies_lock:
                self._applies += 1
            try:
                result, frag = apply_group(payloads)
            finally:
                with self._applies_lock:
                    self._applies -= 1
                if release is not None:
                    for p in payloads:
                        release(p)
            self.pool.advance(applied=1)
            if frag is not None and self.on_apply is not None:
                try:
                    self.on_apply(frag)
                except Exception:
                    # observers must never fail an ingest apply
                    if self.stats is not None:
                        self.stats.count("ingest_on_apply_errors", 1)
            if frag is not None and self.uploader is not None:
                self.pool.note_phase("upload")
                self.uploader.submit(frag)
            return result

        return self.pool.submit_merged(key, payload, fn_many)

    def drain(self, handles):
        """Await every submitted segment; first error raised after all
        settle."""
        self.pool.wait_all(handles)

    @property
    def overlap_frac(self) -> float:
        return self.uploader.overlap_frac if self.uploader is not None else 0.0

    def snapshot(self) -> dict:
        out = {
            "pool": self.pool.snapshot(),
            "staging": self.staging.snapshot(),
            "decoded": self.decoded,
            "decodeSeconds": round(self.decode_seconds, 6),
            "segments": self.segments,
        }
        if self.uploader is not None:
            out["uploader"] = self.uploader.snapshot()
            out["overlapFrac"] = round(self.overlap_frac, 4)
        return out

    def close(self) -> None:
        if self.uploader is not None:
            self.uploader.flush(timeout=5.0)
            self.uploader.close()
