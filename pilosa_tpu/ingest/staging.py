"""Pinned per-shard staging buffers for the ingest decode stage.

A StagingPool owns a small, fixed set of reusable uint64 host buffers.
The decode stage parks each Roaring blob's positions in one of them —
through the native codec's ``rt_deserialize_into`` when available, so
the decoded positions land straight in the reusable buffer with no
intermediate malloc/copy pair per batch ("zero-copy" decode; the Python
fallback pays one copy into the buffer and stays correct).

The pool is deliberately bounded: ``acquire`` blocks when every buffer
is out, which is the decode stage's backpressure (an import can decode
at most ``buffers`` batches ahead of the apply stage).  Buffers are
host-pinned in spirit — on a TPU host these numpy pages are what
``jax.device_put`` DMA-reads, and keeping them alive and reused avoids
both allocator churn and repinning.
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from pilosa_tpu.storage import _native, roaring

# Default buffer capacity in positions (8 bytes each).  Sized for one
# bulk-import batch of a few hundred thousand bits; acquire() grows a
# buffer in place when a bigger blob arrives, and the growth sticks for
# the buffer's lifetime (steady state: no further allocation).
DEFAULT_CAPACITY = 1 << 20


class StagingBuffer:
    """One reusable decode target.  ``positions`` is a view of the
    filled prefix after ``decode``; ``release`` returns the buffer to
    its pool (idempotent)."""

    def __init__(self, pool: "StagingPool", capacity: int):
        self._pool = pool
        self.data = np.empty(capacity, dtype=np.uint64)
        self.n = 0
        self._held = False

    @property
    def capacity(self) -> int:
        return int(self.data.size)

    @property
    def positions(self) -> np.ndarray:
        return self.data[: self.n]

    def ensure(self, capacity: int) -> None:
        if self.data.size < capacity:
            self.data = np.empty(int(capacity), dtype=np.uint64)

    def decode(self, data: bytes) -> int:
        """Decode a Roaring blob into this buffer; returns the position
        count.  Raises roaring.RoaringError on a malformed payload."""
        out = _native.deserialize_into(data, self.data)
        if out is not None:
            self.n = out[0]
            return self.n
        # Python fallback: decode then copy into the pinned buffer so
        # downstream stages see one buffer type either way.
        positions = roaring.deserialize(data)
        self.ensure(positions.size)
        self.data[: positions.size] = positions
        self.n = int(positions.size)
        return self.n

    def decode_grow(self, data: bytes) -> int:
        """``decode`` with the grow-and-retry loop for blobs bigger than
        the buffer (native reports the required capacity)."""
        try:
            return self.decode(data)
        except ValueError as e:
            need = int(str(e).rsplit(" ", 1)[-1])
            self.ensure(max(need, self.capacity * 2))
            return self.decode(data)

    def release(self) -> None:
        self._pool._release(self)


class StagingPool:
    """Bounded pool of StagingBuffers; ``acquire`` blocks when empty."""

    def __init__(
        self,
        buffers: int = 4,
        capacity: int = DEFAULT_CAPACITY,
        stats=None,
    ):
        self.size = max(1, int(buffers))
        self.stats = stats
        self._free: queue.Queue = queue.Queue(maxsize=self.size)
        self._lock = threading.Lock()
        self._outstanding = 0
        self.acquires = 0
        self.blocked_acquires = 0
        self.blocked_seconds = 0.0
        for _ in range(self.size):
            self._free.put(StagingBuffer(self, int(capacity)))

    def acquire(self, timeout: float | None = None) -> StagingBuffer:
        """Take a buffer, blocking while all are out (decode-stage
        backpressure).  Raises queue.Empty on timeout."""
        try:
            buf = self._free.get_nowait()
        except queue.Empty:
            self.blocked_acquires += 1
            t0 = time.perf_counter()
            buf = self._free.get(timeout=timeout)
            dt = time.perf_counter() - t0
            self.blocked_seconds += dt
            if self.stats is not None:
                self.stats.timing("ingest_staging_blocked", dt)
        buf.n = 0
        buf._held = True
        with self._lock:
            self._outstanding += 1
        self.acquires += 1
        if self.stats is not None:
            self.stats.gauge("ingest_staging_outstanding", self.outstanding)
        return buf

    def _release(self, buf: StagingBuffer) -> None:
        with self._lock:
            if not buf._held:
                return  # idempotent: error paths release defensively
            buf._held = False
            self._outstanding -= 1
        self._free.put(buf)
        if self.stats is not None:
            self.stats.gauge("ingest_staging_outstanding", self.outstanding)

    @property
    def outstanding(self) -> int:
        with self._lock:
            return self._outstanding

    def snapshot(self) -> dict:
        return {
            "buffers": self.size,
            "outstanding": self.outstanding,
            "acquires": self.acquires,
            "blockedAcquires": self.blocked_acquires,
            "blockedSeconds": round(self.blocked_seconds, 6),
        }
