"""Device-resident ingest pipeline.

Replaces the lock-step import path (decode -> apply -> device sync,
serialized per batch) with a staged pipeline in the tf.data shape —
overlap the transfer with the compute so neither side ever waits for
the whole of the other:

  decode (zero-copy native Roaring -> pinned staging buffer)
    -> coalesced fragment apply (bounded import pool, same-fragment
       jobs group-committed into one merged apply)
    -> double-buffered host->device upload (batch N+1's HBM upload
       overlaps batch N's apply)

Every stage is bounded, so backpressure propagates stage-by-stage back
to the HTTP client instead of queueing unboundedly.  See docs/ingest.md.
"""

from pilosa_tpu.ingest.pipeline import DeviceUploader, IngestPipeline
from pilosa_tpu.ingest.staging import StagingBuffer, StagingPool

__all__ = [
    "DeviceUploader",
    "IngestPipeline",
    "StagingBuffer",
    "StagingPool",
]
