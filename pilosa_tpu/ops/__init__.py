"""Device-side bitmap kernels: the TPU replacement for the reference's
roaring container op matrix (reference: roaring/roaring.go:3078-4414)."""
