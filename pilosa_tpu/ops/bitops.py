"""Core bitmap word operations.

The reference implements a per-container-type op matrix (array/bitmap/run ×
intersect/union/difference/xor, reference roaring/roaring.go:3078-4414 and
popcount :5057). On TPU every fragment row is a dense little-endian word
vector ``uint32[SHARD_WORDS]``, so the whole matrix collapses to vectorized
bitwise ops + ``lax.population_count``, which XLA fuses and tiles onto the
VPU. Host-side helpers convert between column-id lists and packed words
(numpy) for ingest/serialization.

Bit addressing: column offset ``c`` within a shard lives at word ``c >> 5``,
bit ``c & 31`` (little-endian within the word). With numpy little-endian
``uint32 -> uint8`` views plus ``np.unpackbits(bitorder="little")`` this
means flat bit index == column offset.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from pilosa_tpu.shardwidth import SHARD_WORDS, WORD_BITS

# ---------------------------------------------------------------------------
# Host-side (numpy) packing helpers — the ingest/serialization boundary.
# ---------------------------------------------------------------------------


def pow2_pad_len(n: int) -> int:
    """Power-of-two bucket for padding batch/scatter shapes so jit
    programs are reused across drifting sizes; 1 for n <= 1."""
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def pack_columns(cols: np.ndarray, n_words: int = SHARD_WORDS) -> np.ndarray:
    """Pack a sorted-or-not array of column offsets into uint32 words."""
    words = np.zeros(n_words, dtype=np.uint32)
    if len(cols) == 0:
        return words
    cols = np.asarray(cols, dtype=np.int64)
    w = cols >> 5
    b = (cols & 31).astype(np.uint32)
    np.bitwise_or.at(words, w, np.uint32(1) << b)
    return words


def unpack_columns(words: np.ndarray) -> np.ndarray:
    """Inverse of :func:`pack_columns`: packed words -> sorted column offsets."""
    words = np.ascontiguousarray(words, dtype=np.uint32)
    bits = np.unpackbits(words.view(np.uint8), bitorder="little")
    return np.flatnonzero(bits).astype(np.uint64)


def pack_positions(positions: np.ndarray, n_words: int) -> tuple[np.ndarray, np.ndarray]:
    """Group absolute bit positions (row*SHARD_WIDTH + col) into (rows, words).

    Returns ``(row_ids, words[len(row_ids), n_words])`` — one packed word
    vector per distinct row. Used to turn op-log batches into device updates.
    """
    positions = np.asarray(positions, dtype=np.uint64)
    shard_width = np.uint64(n_words * WORD_BITS)
    rows = positions // shard_width
    offs = positions % shard_width
    row_ids, inverse = np.unique(rows, return_inverse=True)
    words = np.zeros((len(row_ids), n_words), dtype=np.uint32)
    w = (offs >> np.uint64(5)).astype(np.int64)
    b = (offs & np.uint64(31)).astype(np.uint32)
    np.bitwise_or.at(words, (inverse, w), np.uint32(1) << b)
    return row_ids, words


def popcount_host(words: np.ndarray) -> int:
    """Host popcount over a word array (any shape) — native single-pass
    kernel (native/hostops.cpp), numpy ``bitwise_count`` fallback."""
    from pilosa_tpu.ops import _hostops

    return _hostops.popcount(words)


def pair_count_host(a: np.ndarray, b: np.ndarray, op: str) -> int:
    """Fused host ``popcount(op(a, b))`` with no materialized temporary
    — the latency-tier twin of the jitted ``*_count`` kernels below
    (reference roaring.go:568's word loop). ``op`` is one of
    intersect/union/difference/xor."""
    from pilosa_tpu.ops import _hostops

    return _hostops.pair_count(a, b, op)


def shift_row_host(words: np.ndarray, n: int = 1) -> np.ndarray:
    """Host twin of :func:`shift_row`: shift bits toward higher column
    ids, dropping bits past the shard edge."""
    words = np.asarray(words, dtype=np.uint32)
    nw = words.shape[-1]
    n = int(n)
    if n <= 0:
        return words.copy()
    word_shift, bit_shift = divmod(n, WORD_BITS)
    out = np.zeros_like(words)
    if word_shift < nw:
        out[..., word_shift:] = words[..., : nw - word_shift]
    if bit_shift:
        carry = np.zeros_like(out)
        carry[..., 1:] = out[..., :-1] >> np.uint32(WORD_BITS - bit_shift)
        out = ((out << np.uint32(bit_shift)) | carry).astype(np.uint32)
    return out


# ---------------------------------------------------------------------------
# Device-side (jitted) kernels.
# ---------------------------------------------------------------------------


def popcount(words: jax.Array) -> jax.Array:
    """Per-word population count (uint32 in, uint32 out)."""
    return lax.population_count(words)


@jax.jit  # graftlint: disable=launch-discipline -- word-level helpers; callers dispatch them beneath ops.kernels/executor ledger windows
def count_bits(words: jax.Array) -> jax.Array:
    """Total set bits in a word tensor -> int32 scalar.

    Safe while total <= 2^31; per-shard counts (<= 2^20 * rows bits) always
    fit. Cross-shard totals are summed host-side in Python ints.
    """
    return jnp.sum(lax.population_count(words).astype(jnp.int32))


@jax.jit  # graftlint: disable=launch-discipline -- word-level helpers; callers dispatch them beneath ops.kernels/executor ledger windows
def count_rows(bits: jax.Array) -> jax.Array:
    """Row-wise popcount: ``uint32[..., rows, W] -> int32[..., rows]``.

    The TPU replacement for the reference's per-row cache recount
    (reference fragment.go:459-498).
    """
    return jnp.sum(lax.population_count(bits).astype(jnp.int32), axis=-1)


@jax.jit  # graftlint: disable=launch-discipline -- word-level helpers; callers dispatch them beneath ops.kernels/executor ledger windows
def intersection_count(a: jax.Array, b: jax.Array) -> jax.Array:
    """popcount(a & b) without materializing the AND (XLA fuses the chain).

    Replaces the per-type-pair ``intersectionCount*`` kernels
    (reference roaring/roaring.go:568, 3078+).
    """
    return jnp.sum(lax.population_count(a & b).astype(jnp.int32))


@jax.jit  # graftlint: disable=launch-discipline -- word-level helpers; callers dispatch them beneath ops.kernels/executor ledger windows
def union_count(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.sum(lax.population_count(a | b).astype(jnp.int32))


@jax.jit  # graftlint: disable=launch-discipline -- word-level helpers; callers dispatch them beneath ops.kernels/executor ledger windows
def difference_count(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.sum(lax.population_count(a & ~b).astype(jnp.int32))


@jax.jit  # graftlint: disable=launch-discipline -- word-level helpers; callers dispatch them beneath ops.kernels/executor ledger windows
def xor_count(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.sum(lax.population_count(a ^ b).astype(jnp.int32))


def zero_row(n_words: int = SHARD_WORDS) -> jax.Array:
    return jnp.zeros((n_words,), dtype=jnp.uint32)


@jax.jit  # graftlint: disable=launch-discipline -- word-level helpers; callers dispatch them beneath ops.kernels/executor ledger windows
def shift_row(words: jax.Array, n: jax.Array | int = 1) -> jax.Array:
    """Shift all bits toward higher column ids by ``n`` (reference
    roaring.go:944 ``Shift``; only n=1 is used by PQL's Shift call, but the
    kernel is general). Bits shifted past the shard edge are dropped —
    cross-shard carry is handled by the executor like the reference's
    per-shard Shift."""
    n = jnp.asarray(n, dtype=jnp.uint32)
    word_shift = (n // WORD_BITS).astype(jnp.int32)
    bit_shift = n % WORD_BITS
    # Shift whole words first (roll + mask), then bits with carry.
    idx = jnp.arange(words.shape[-1], dtype=jnp.int32)
    rolled = jnp.roll(words, word_shift, axis=-1)
    rolled = jnp.where(idx >= word_shift, rolled, jnp.uint32(0))
    hi = rolled << bit_shift
    carry_src = jnp.roll(rolled, 1, axis=-1)
    carry_src = jnp.where(idx >= 1, carry_src, jnp.uint32(0))
    lo = jnp.where(
        bit_shift > 0,
        carry_src >> (jnp.uint32(WORD_BITS) - bit_shift),
        jnp.uint32(0),
    )
    return hi | lo


def range_mask(start: int, stop: int, n_words: int = SHARD_WORDS) -> np.ndarray:
    """Host-built mask with bits [start, stop) set — used for flips/ranges
    clipped to the shard (reference roaring.go:1727 ``Flip``)."""
    words = np.zeros(n_words, dtype=np.uint32)
    if stop <= start:
        return words
    first_w, last_w = start >> 5, (stop - 1) >> 5
    words[first_w : last_w + 1] = np.uint32(0xFFFFFFFF)
    words[first_w] &= np.uint32(0xFFFFFFFF) << np.uint32(start & 31)
    if stop & 31:
        words[last_w] &= np.uint32((1 << (stop & 31)) - 1)
    return words
